"""Metric history ring, background sampler, SLO burn-rate engine, and
the runtime regression sentinel (ISSUE 18 tentpole + satellites)."""

from __future__ import annotations

import json
import threading
import time

import pytest

from distllm_tpu.observability import instruments as _metrics
from distllm_tpu.observability.baseline import (
    ENVELOPE_SCHEMA,
    build_envelope,
    envelope_from_records,
    extract_metrics,
    load_envelope,
)
from distllm_tpu.observability.flight import FlightRecorder
from distllm_tpu.observability.history import (
    SAMPLER_THREAD_NAME,
    HistorySampler,
    MetricsHistory,
    get_metrics_history,
    history_excerpt,
    series_key,
)
from distllm_tpu.observability.metrics import MetricsRegistry
from distllm_tpu.observability.sentinel import RegressionSentinel
from distllm_tpu.observability.slo import (
    burn_rate,
    slo_status,
    update_burn_gauges,
)


def _fresh() -> tuple[MetricsRegistry, MetricsHistory]:
    registry = MetricsRegistry()
    return registry, MetricsHistory(registry, capacity=64)


# ------------------------------------------------------------------- ring
def test_series_key_sorts_labels():
    assert series_key('m') == 'm'
    assert series_key('m', {'b': '2', 'a': '1'}) == 'm{a=1,b=2}'


def test_counter_history_deltas_and_rates():
    registry, history = _fresh()
    c = registry.counter('test_tokens_total')
    c.inc(10)
    history.sample_once(now=100.0)  # first sighting: no interval yet
    c.inc(20)
    history.sample_once(now=101.0)
    c.inc(5)
    history.sample_once(now=103.0)
    win = history.counter_window('test_tokens_total', 10.0, now=103.0)
    assert win['delta'] == 25.0
    assert win['covered_s'] == pytest.approx(3.0)
    assert win['rate'] == pytest.approx(25.0 / 3.0)
    # A narrower window isolates the newest tick only.
    narrow = history.counter_window('test_tokens_total', 1.5, now=103.0)
    assert narrow['delta'] == 5.0
    assert narrow['rate'] == pytest.approx(2.5)
    # Counter reset (process restart): delta clamps to 0, never negative.
    c._default_child()._value = 1.0  # simulate a post-restart lower reading
    history.sample_once(now=104.0)
    after = history.counter_window('test_tokens_total', 0.9, now=104.0)
    assert after['delta'] == 0.0


def test_counter_history_unseen_series_is_empty():
    _, history = _fresh()
    win = history.counter_window('never_seen_total', 60.0, now=1.0)
    assert win == {
        'delta': 0, 'rate': None, 'covered_s': 0, 'points': 0,
    }
    assert history.counter_rate('never_seen_total', 60.0) is None


def test_gauge_history_window_aggregates():
    registry, history = _fresh()
    g = registry.gauge('test_depth')
    for now, value in ((1.0, 2.0), (2.0, 8.0), (3.0, 4.0)):
        g.set(value)
        history.sample_once(now=now)
    assert history.gauge_window('test_depth', 10, now=3.0) == pytest.approx(
        14.0 / 3.0
    )
    assert history.gauge_window('test_depth', 10, agg='last', now=3.0) == 4.0
    assert history.gauge_window('test_depth', 10, agg='min', now=3.0) == 2.0
    assert history.gauge_window('test_depth', 10, agg='max', now=3.0) == 8.0
    assert history.gauge_window('test_depth', 0.5, now=0.0) is None
    with pytest.raises(ValueError):
        history.gauge_window('test_depth', 10, agg='median', now=3.0)


def test_labeled_series_are_independent():
    registry, history = _fresh()
    c = registry.counter('test_by_kind_total', labelnames=('kind',))
    c.labels(kind='a').inc(1)
    c.labels(kind='b').inc(1)
    history.sample_once(now=1.0)
    c.labels(kind='a').inc(9)
    history.sample_once(now=2.0)
    a = history.counter_window(
        'test_by_kind_total', 10, labels={'kind': 'a'}, now=2.0
    )
    b = history.counter_window(
        'test_by_kind_total', 10, labels={'kind': 'b'}, now=2.0
    )
    assert a['delta'] == 9.0
    assert b['delta'] == 0.0


def test_histogram_window_quantile_isolates_window():
    """The tentpole quantile contract: a trailing window's quantile
    covers ONLY that window's observations — warmup noise before it must
    not leak in (the delta-cumulative estimator)."""
    registry, history = _fresh()
    h = registry.histogram('test_lat_seconds', buckets=(1.0, 2.0, 4.0))
    history.sample_once(now=100.0)  # baseline snapshot (no point yet)
    h.observe(0.5)  # pre-window noise, lands in tick 2's interval
    history.sample_once(now=101.0)
    for _ in range(10):
        h.observe(3.0)
    history.sample_once(now=102.0)
    p50 = history.window_quantile('test_lat_seconds', 0.5, 1.5, now=102.0)
    assert 2.0 < p50 <= 4.0  # the 0.5 s observation is excluded
    # A window spanning both ticks sees the noise too.
    p5 = history.window_quantile('test_lat_seconds', 0.05, 10.0, now=102.0)
    assert p5 <= 1.0
    # An idle window has no observations: None, never a division.
    history.sample_once(now=103.0)
    assert (
        history.window_quantile('test_lat_seconds', 0.95, 0.5, now=103.0)
        is None
    )
    assert history.window_quantile('missing_seconds', 0.5, 10.0) is None


def test_history_capacity_bounds_every_ring():
    registry = MetricsRegistry()
    history = MetricsHistory(registry, capacity=4)
    c = registry.counter('test_bounded_total')
    for i in range(10):
        c.inc()
        history.sample_once(now=float(i))
    win = history.counter_window('test_bounded_total', 1e9, now=9.0)
    assert win['points'] == 4  # oldest points evicted, never unbounded
    with pytest.raises(ValueError):
        MetricsHistory(registry, capacity=1)


def test_snapshot_schema_and_filters():
    registry, history = _fresh()
    registry.counter('test_snap_total').inc(2)
    registry.gauge('test_snap_depth').set(3.0)
    h = registry.histogram('test_snap_seconds', buckets=(1.0,))
    h.observe(0.5)
    history.sample_once(now=1.0)
    h.observe(0.7)
    registry.counter('test_snap_total').inc(1)
    history.sample_once(now=2.0)
    snap = history.snapshot()
    assert snap['schema'] == 'distllm-history/v1'
    assert snap['capacity'] == 64
    assert snap['samples'] == 2
    assert snap['quantiles'] == [0.5, 0.95, 0.99]
    counter = snap['series']['test_snap_total']
    assert counter['kind'] == 'counter'
    # [t, delta, rate] — the first sighting produced no point.
    assert counter['points'] == [[2.0, 1.0, 1.0]]
    gauge = snap['series']['test_snap_depth']
    assert gauge['points'] == [[1.0, 3.0], [2.0, 3.0]]
    hist = snap['series']['test_snap_seconds']
    (point,) = hist['points']
    t, count_delta, rate, p50, p95, p99 = point
    assert (t, count_delta, rate) == (2.0, 1, 1.0)
    assert p50 is not None and p50 <= 1.0
    # prefix filter + per-series limit
    only = history.snapshot(prefix='test_snap_t')
    assert list(only['series']) == ['test_snap_total']
    trimmed = history.snapshot(limit=1)
    assert len(trimmed['series']['test_snap_depth']['points']) == 1
    # The document is JSON-serializable as-is (the endpoint contract).
    json.dumps(snap)


def test_histogram_idle_tick_renders_null_quantiles():
    registry, history = _fresh()
    h = registry.histogram('test_idle_seconds', buckets=(1.0,))
    h.observe(0.5)
    history.sample_once(now=1.0)
    history.sample_once(now=2.0)  # no new observations this interval
    history.sample_once(now=3.0)
    points = history.snapshot()['series']['test_idle_seconds']['points']
    assert [p[1] for p in points] == [0, 0]
    assert all(p[3] is None for p in points)  # p50 null, not 0/0


def test_clear_drops_points_and_delta_state():
    registry, history = _fresh()
    c = registry.counter('test_clear_total')
    c.inc(5)
    history.sample_once(now=1.0)
    c.inc(5)
    history.sample_once(now=2.0)
    history.clear()
    assert history.samples == 0
    assert history.snapshot()['series'] == {}
    # Post-clear the next tick is a first sighting again: no giant delta.
    history.sample_once(now=3.0)
    assert history.counter_window('test_clear_total', 10, now=3.0)[
        'delta'
    ] == 0


def test_observer_runs_after_tick_and_errors_are_counted():
    registry, history = _fresh()
    registry.counter('test_obs_total').inc()
    seen: list[float] = []

    def ok_observer(h, now):
        # Observers run OUTSIDE the ring lock: window helpers (which
        # take the lock) must be callable from here without deadlock.
        h.counter_window('test_obs_total', 10.0, now=now)
        seen.append(now)

    def bad_observer(h, now):
        raise RuntimeError('observer exploded')

    history.add_observer(ok_observer)
    history.add_observer(bad_observer)
    errors_before = _metrics.HISTORY_SAMPLE_ERRORS.value
    history.sample_once(now=1.0)
    history.sample_once(now=2.0)
    assert seen == [1.0, 2.0]
    assert _metrics.HISTORY_SAMPLE_ERRORS.value == errors_before + 2
    history.remove_observer(ok_observer)
    history.sample_once(now=3.0)
    assert seen == [1.0, 2.0]


def test_sample_overhead_bound():
    """The documented overhead bound: one full-catalog tick (the REAL
    process registry, every instrument the repo registers) stays under
    50 ms — at the default 1 s interval that is <5% of one core even
    with a 10x margin for loaded machines."""
    history = MetricsHistory()  # the full default registry
    history.sample_once()  # warm allocation paths
    start = time.perf_counter()
    ticks = 5
    for _ in range(ticks):
        history.sample_once()
    per_tick = (time.perf_counter() - start) / ticks
    assert per_tick < 0.05, f'sampler tick took {per_tick:.4f}s'


# ---------------------------------------------------------------- sampler
def test_sampler_thread_lifecycle_no_leak():
    registry, history = _fresh()
    registry.counter('test_sampled_total').inc()
    sampler = HistorySampler(history, interval_s=0.01)
    assert not sampler.running
    sampler.start()
    assert sampler.running
    assert any(
        t.name == SAMPLER_THREAD_NAME for t in threading.enumerate()
    )
    assert history.interval_hint_s == 0.01
    with pytest.raises(RuntimeError):
        sampler.start()  # double start is a bug, not a silent no-op
    deadline = time.time() + 5.0
    while history.samples < 3 and time.time() < deadline:
        time.sleep(0.01)
    assert history.samples >= 3
    sampler.stop()
    sampler.stop()  # idempotent
    assert not sampler.running
    assert not any(
        t.name == SAMPLER_THREAD_NAME for t in threading.enumerate()
    )
    # Restartable after a clean stop (the bench identity arm pattern).
    sampler.start()
    assert sampler.running
    sampler.stop()
    assert not sampler.running


def test_sampler_context_manager_and_validation():
    registry, history = _fresh()
    with HistorySampler(history, interval_s=0.01) as sampler:
        assert sampler.running
    assert not sampler.running
    with pytest.raises(ValueError):
        HistorySampler(history, interval_s=0.0)


def test_engine_owns_sampler_only_when_configured():
    """EngineConfig.history_interval_s > 0 starts a sampler in __init__
    and shutdown() joins it — no leaked thread after engine shutdown
    (the ISSUE 18 acceptance assert)."""
    jax = pytest.importorskip('jax')
    from distllm_tpu.generate.engine.engine import EngineConfig, LLMEngine
    from distllm_tpu.models import mistral

    cfg = mistral.MistralConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=64, dtype='float32',
    )
    params = mistral.init(jax.random.PRNGKey(0), cfg)

    class IdTokenizer:
        eos_id = None

    engine = LLMEngine(
        cfg, params, IdTokenizer(),
        EngineConfig(
            block_size=4, num_blocks=16, max_num_seqs=2, max_model_len=32,
            prefer_native_allocator=False, decode_layer_unroll=False,
            history_interval_s=0.05,
        ),
    )
    try:
        assert engine._history_sampler is not None
        assert engine._history_sampler.running
        assert any(
            t.name == SAMPLER_THREAD_NAME for t in threading.enumerate()
        )
    finally:
        engine.shutdown()
    assert engine._history_sampler is None
    assert not any(
        t.name == SAMPLER_THREAD_NAME for t in threading.enumerate()
    )
    with pytest.raises(Exception):
        EngineConfig(history_interval_s=-1.0)


# -------------------------------------------------------------------- slo
def _slo_history(met: int, missed: int) -> MetricsHistory:
    registry = MetricsRegistry()
    slo = registry.counter(
        'distllm_request_slo_total', labelnames=('outcome',)
    )
    slo.labels(outcome='met')  # pre-register both children
    slo.labels(outcome='missed')
    history = MetricsHistory(registry)
    history.sample_once(now=1000.0)
    slo.labels(outcome='met').inc(met)
    slo.labels(outcome='missed').inc(missed)
    history.sample_once(now=1010.0)
    return history


def test_burn_rate_math():
    history = _slo_history(met=90, missed=10)
    burn = burn_rate(history, 60.0, objective=0.99, now=1010.0)
    assert burn['met'] == 90 and burn['missed'] == 10
    # 10% miss fraction against a 1% budget: burning 10x too fast.
    assert burn['burn_rate'] == pytest.approx(10.0)
    with pytest.raises(ValueError):
        burn_rate(history, 60.0, objective=1.5)


def test_burn_rate_zero_traffic_is_zero():
    history = _slo_history(met=0, missed=0)
    burn = burn_rate(history, 60.0, now=1010.0)
    assert burn['total'] == 0
    assert burn['burn_rate'] == 0.0  # an idle replica burns no budget


def test_slo_status_verdicts_and_gauges():
    history = _slo_history(met=50, missed=50)  # burn 50x: on fire
    burns = update_burn_gauges(history, now=1010.0)
    assert set(burns) == set(_metrics.SLO_BURN_WINDOW_LABELS)
    assert burns['60s'] == pytest.approx(50.0)
    assert _metrics.SLO_BURN_RATE.labels(window='60s').value == (
        pytest.approx(50.0)
    )
    doc = slo_status(history, now=1010.0)
    assert doc['schema'] == 'distllm-slo/v1'
    assert doc['verdict'] == 'page'
    firing = [p for p in doc['pairs'] if p['firing']]
    assert any(p['verdict'] == 'page' for p in firing)
    assert doc['goodput_fraction'] is None  # no token counters here
    json.dumps(doc)

    quiet = _slo_history(met=1000, missed=0)
    assert slo_status(quiet, now=1010.0)['verdict'] == 'ok'
    # Slow burn: past 1.0 (warn pair) but under 6.0 (page pair).
    warm = _slo_history(met=97, missed=3)
    assert slo_status(warm, now=1010.0)['verdict'] == 'warn'


# --------------------------------------------------------------- baseline
def test_extract_metrics_drops_non_numeric():
    metrics = extract_metrics({
        'tok_s': 100.0, 'n': 3, 'ok': True, 'name': 'r', 'bad': float('nan'),
    })
    assert metrics == {'tok_s': 100.0, 'n': 3.0}
    assert extract_metrics(None) == {}


def test_build_envelope_prefers_best_source_key():
    envelope = build_envelope(
        {
            'gen_load_tok_s': 800.0,
            'gen_value': 180.0,  # the fallback must NOT win
            'gen_load_ttft_p95': 0.5,
            'unrelated': 3.0,
        },
        source='r09',
    )
    assert envelope['schema'] == ENVELOPE_SCHEMA
    assert envelope['source'] == 'r09'
    tok = envelope['metrics']['tok_s']
    assert tok == {
        'value': 800.0, 'direction': 'higher', 'from_key': 'gen_load_tok_s',
    }
    assert envelope['metrics']['ttft_p95_s']['direction'] == 'lower'
    assert 'mfu_measured' not in envelope['metrics']


def test_envelope_from_records_newest_usable_wins():
    records = [
        {'name': 'r01', 'metrics': {'gen_value': 100.0}},
        {'name': 'r02', 'metrics': {'gen_value': 184.0}},
        {'name': 'r03', 'metrics': {}},  # the crashed tail
    ]
    envelope = envelope_from_records(records)
    assert envelope['source'] == 'r02'
    assert envelope['metrics']['tok_s']['value'] == 184.0
    empty = envelope_from_records([{'name': 'r03', 'metrics': {}}])
    assert empty['metrics'] == {}
    assert envelope_from_records([]) == {
        'schema': ENVELOPE_SCHEMA, 'source': '', 'metrics': {},
    }


def test_load_envelope_roundtrip_and_degraded_modes(tmp_path):
    envelope = build_envelope({'gen_load_tok_s': 500.0}, source='r08')
    path = tmp_path / 'baseline.json'
    path.write_text(json.dumps(envelope))
    loaded = load_envelope(path)
    assert loaded['metrics']['tok_s']['value'] == 500.0
    assert load_envelope(tmp_path / 'missing.json') is None
    (tmp_path / 'junk.json').write_text('{not json')
    assert load_envelope(tmp_path / 'junk.json') is None
    (tmp_path / 'wrong.json').write_text(json.dumps({'schema': 'other/v1'}))
    assert load_envelope(tmp_path / 'wrong.json') is None
    # Non-numeric values are dropped, not served to the sentinel.
    (tmp_path / 'dirty.json').write_text(json.dumps({
        'schema': ENVELOPE_SCHEMA,
        'source': 'x',
        'metrics': {'tok_s': {'value': 'fast'}, 'ttft_p95_s': {'value': 1.0}},
    }))
    dirty = load_envelope(tmp_path / 'dirty.json')
    assert list(dirty['metrics']) == ['ttft_p95_s']


# --------------------------------------------------------------- sentinel
def _token_history(rate_tok_s: float) -> tuple[MetricsRegistry, MetricsHistory]:
    registry = MetricsRegistry()
    c = registry.counter('distllm_engine_generated_tokens_total')
    history = MetricsHistory(registry)
    history.sample_once(now=1000.0)
    c.inc(rate_tok_s * 10.0)
    history.sample_once(now=1010.0)
    return registry, history


def test_sentinel_fires_once_per_episode_and_unlatches():
    registry, history = _token_history(rate_tok_s=40.0)  # 60% below baseline
    recorder = FlightRecorder(capacity=16)
    fired_before = _metrics.SENTINEL_REGRESSIONS.labels(
        metric='tok_s'
    ).value
    sentinel = RegressionSentinel(
        history,
        envelope=build_envelope({'gen_load_tok_s': 100.0}, source='r'),
        threshold=0.2,
        # One tick interval wide, so each evaluate() judges exactly the
        # newest point — episodes flip cleanly between samples.
        window_s=9.0,
        recorder=recorder,
    )
    assert sentinel.armed
    assert _metrics.SENTINEL_ARMED.value == 1.0
    events = sentinel.evaluate(now=1010.0)
    assert [e['metric'] for e in events] == ['tok_s']
    assert events[0]['baseline'] == 100.0
    assert events[0]['live'] == pytest.approx(40.0)
    assert sentinel.evaluate(now=1010.0) == []  # latched: once per episode
    assert _metrics.SENTINEL_REGRESSIONS.labels(metric='tok_s').value == (
        fired_before + 1
    )
    # The counted flight record (the 'regression' kind).
    kinds = [r['kind'] for r in recorder.snapshot()]
    assert kinds == ['regression']
    # Recovery unlatches; the NEXT degradation fires a fresh episode.
    c = registry.get('distllm_engine_generated_tokens_total')
    c.inc(100.0 * 10.0)
    history.sample_once(now=1020.0)
    assert sentinel.evaluate(now=1020.0) == []  # recovered, silent
    c.inc(10.0)
    history.sample_once(now=1030.0)
    refired = sentinel.evaluate(now=1030.0)
    assert [e['metric'] for e in refired] == ['tok_s']
    status = sentinel.status(now=1030.0)
    assert status['armed'] and status['degraded'] == ['tok_s']
    assert status['fired_total'] == 2
    json.dumps(status)


def test_sentinel_never_fires_without_traffic():
    registry = MetricsRegistry()
    registry.counter('distllm_engine_generated_tokens_total')
    history = MetricsHistory(registry)
    history.sample_once(now=1000.0)
    history.sample_once(now=1010.0)  # idle ticks: delta 0
    sentinel = RegressionSentinel(
        history,
        envelope=build_envelope(
            {'gen_load_tok_s': 100.0, 'gen_load_ttft_p95': 0.2}, source='r'
        ),
        recorder=FlightRecorder(capacity=4),
    )
    assert sentinel.evaluate(now=1010.0) == []


def test_sentinel_lower_better_direction():
    registry = MetricsRegistry()
    h = registry.histogram(
        'distllm_request_ttft_seconds', buckets=(0.1, 1.0, 10.0)
    )
    history = MetricsHistory(registry)
    history.sample_once(now=1000.0)
    for _ in range(20):
        h.observe(5.0)  # way above the 0.2 s baseline
    history.sample_once(now=1010.0)
    sentinel = RegressionSentinel(
        history,
        envelope=build_envelope({'gen_load_ttft_p95': 0.2}, source='r'),
        window_s=60.0,
        recorder=FlightRecorder(capacity=4),
    )
    events = sentinel.evaluate(now=1010.0)
    assert [e['metric'] for e in events] == ['ttft_p95_s']
    assert events[0]['direction'] == 'lower'


def test_sentinel_disarmed_modes_are_counted_never_raised(tmp_path):
    _, history = _token_history(rate_tok_s=100.0)

    def disarms(reason: str) -> float:
        return _metrics.SENTINEL_DISARMED.labels(reason=reason).value

    before_nb = disarms('no_baseline')
    sentinel = RegressionSentinel(history, recorder=FlightRecorder(capacity=4))
    # Plain construction without an envelope is NOT a counted disarm.
    assert not sentinel.armed
    assert disarms('no_baseline') == before_nb
    # Missing baseline file: counted, evaluate stays a no-op.
    assert sentinel.arm_from_file(tmp_path / 'missing.json') is False
    assert disarms('no_baseline') == before_nb + 1
    assert _metrics.SENTINEL_ARMED.value == 0.0
    assert sentinel.evaluate(now=1010.0) == []
    # An envelope with no usable metrics: the 'empty' reason.
    before_empty = disarms('empty')
    assert sentinel.arm({'schema': ENVELOPE_SCHEMA, 'metrics': {}}) is False
    assert disarms('empty') == before_empty + 1
    # Arming with a real envelope recovers.
    assert sentinel.arm(
        build_envelope({'gen_load_tok_s': 100.0}, source='r')
    )
    assert sentinel.armed and _metrics.SENTINEL_ARMED.value == 1.0


def test_sentinel_driven_by_sampler_observer():
    registry, history = _token_history(rate_tok_s=10.0)
    recorder = FlightRecorder(capacity=4)
    sentinel = RegressionSentinel(
        history,
        envelope=build_envelope({'gen_load_tok_s': 100.0}, source='r'),
        window_s=60.0,
        recorder=recorder,
    ).install()
    history.sample_once(now=1011.0)  # the tick drives evaluate()
    assert [r['kind'] for r in recorder.snapshot()] == ['regression']
    sentinel.uninstall()
    registry.get('distllm_engine_generated_tokens_total').inc(1)
    history.sample_once(now=1012.0)
    assert len(recorder.snapshot()) == 1  # uninstalled: no more evals


# ------------------------------------------------------------- integration
def test_history_excerpt_shape():
    registry = MetricsRegistry()
    c = registry.counter('distllm_engine_generated_tokens_total')
    history = MetricsHistory(registry)
    history.sample_once(now=1000.0)
    c.inc(500)
    history.sample_once(now=1010.0)
    excerpt = history_excerpt(history, window_s=60.0, now=1010.0)
    assert excerpt['tok_s'] == pytest.approx(50.0)
    assert excerpt['samples'] == 2
    assert excerpt['tok_points']  # [t, rate] rows
    assert isinstance(excerpt['burn_rates'], dict)
    json.dumps(excerpt)


def test_debug_bundle_carries_history_and_slo(tmp_path):
    from distllm_tpu.observability import dump_debug_bundle

    get_metrics_history().sample_once()
    paths = dump_debug_bundle(str(tmp_path / 'bundle'), reason='test')
    assert {'history', 'slo'} <= set(paths)
    history_doc = json.loads(
        (tmp_path / 'bundle' / 'history.json').read_text()
    )
    assert history_doc['schema'] == 'distllm-history/v1'
    assert history_doc['samples'] >= 1
    slo_doc = json.loads((tmp_path / 'bundle' / 'slo.json').read_text())
    assert slo_doc['slo']['schema'] == 'distllm-slo/v1'
    assert slo_doc['slo']['verdict'] in ('ok', 'warn', 'page')
    assert 'sentinel' in slo_doc


def test_perfetto_history_counter_track():
    from distllm_tpu.observability import to_trace_events, validate_trace_events

    registry = MetricsRegistry()
    c = registry.counter('distllm_engine_generated_tokens_total')
    g = registry.gauge('distllm_scheduler_queue_depth')
    history = MetricsHistory(registry)
    history.sample_once(now=1000.0)
    c.inc(100)
    g.set(3.0)
    history.sample_once(now=1001.0)
    doc = to_trace_events([], history=history, time_origin_s=1000.0)
    counters = [e for e in doc['traceEvents'] if e.get('ph') == 'C']
    assert counters, 'history produced no counter events'
    assert {e['cat'] for e in counters} == {'history'}
    by_name = {e['name'] for e in counters}
    assert 'tok/s' in by_name and 'queue_depth' in by_name
    tok = [e for e in counters if e['name'] == 'tok/s']
    assert tok[0]['args']['value'] == pytest.approx(100.0)
    problems = validate_trace_events(doc)
    assert problems == [], problems
    # A pre-rendered snapshot dict works too (the bundle path).
    doc2 = to_trace_events(
        [], history=history.snapshot(), time_origin_s=1000.0
    )
    assert any(e.get('ph') == 'C' for e in doc2['traceEvents'])


def test_build_info_and_uptime_instruments():
    from distllm_tpu import __version__
    from distllm_tpu.observability.metrics import get_registry

    rendered = get_registry().render()
    assert 'distllm_build_info{version="%s"} 1' % __version__ in rendered
    assert 'distllm_server_uptime_seconds' in rendered


def test_gen_history_stage_cpu_smoke(tmp_path):
    """Acceptance smoke (ISSUE 18): the gen_history bench stage completes
    on CPU — the injected slow_window slowdown trips the sentinel, the
    clean arm trips nothing, the latch holds (no re-fire storm), burn
    gauges move under the overload arm, history on/off runs are
    token-identical, and the sampler thread does not leak. Run directly:
    ``JAX_PLATFORMS=cpu DISTLLM_BENCH_SMALL=1 python bench.py --stage
    gen_history``."""
    import os
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS='cpu',
        DISTLLM_BENCH_SMALL='1',
        DISTLLM_BENCH_RECORD_DIR=str(tmp_path),
        DISTLLM_BENCH_BUNDLE_DIR=str(tmp_path / 'bundles'),
        DISTLLM_BENCH_WATCHDOG_S='0',
    )
    env.pop('DISTLLM_FAULTS', None)  # the stage arms its own slowdown
    env.pop('DISTLLM_BENCH_HISTORY', None)  # the skip knob must not hide it
    proc = subprocess.run(
        [sys.executable, str(repo / 'bench.py'), '--stage', 'gen_history'],
        capture_output=True, text=True, timeout=420, cwd=repo, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    fragment = json.loads(proc.stdout.strip().splitlines()[-1])
    assert 'gen_history_error' not in fragment, (
        fragment.get('gen_history_error')
    )
    assert fragment['gen_history_tokens_identical'] is True
    assert fragment['gen_history_clean_regressions'] == 0
    assert fragment['gen_history_slow_regressions'] >= 1
    assert fragment['gen_history_slow_relatch_regressions'] == 0
    assert fragment['gen_history_burn_60s'] > 0
    assert fragment['gen_history_slo_verdict'] == 'page'
    assert fragment['gen_history_shed_requests'] > 0
    assert fragment['gen_history_sampler_leaked'] is False
    assert fragment['gen_history_tok_s'] > 0
