"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip sharding is exercised on CPU via
``--xla_force_host_platform_device_count=8`` (the reference has no multi-node
tests at all — SURVEY.md section 4; we do better by running every collective
path on a virtual mesh in CI).

In this environment a ``sitecustomize`` hook registers a real-TPU PJRT
backend at interpreter start and forces ``jax.config.jax_platforms`` to
``"axon,cpu"`` — which wins over the ``JAX_PLATFORMS`` env var. Undo it
through the same config API before any backend is selected.
"""

import os

os.environ.setdefault('TOKENIZERS_PARALLELISM', 'false')
flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=8'
    ).strip()

import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope='session', autouse=True)
def _assert_cpu():
    devices = jax.devices()
    assert devices[0].platform == 'cpu', devices
    assert len(devices) == 8, devices
    yield


@pytest.fixture(scope='session')
def rng():
    return np.random.default_rng(0)
