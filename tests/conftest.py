"""Test configuration: force an 8-device virtual CPU mesh before jax imports.

Multi-chip sharding is exercised on CPU via
``--xla_force_host_platform_device_count=8`` (the reference has no multi-node
tests at all — SURVEY.md section 4; we do better by running every collective
path on a virtual mesh in CI).
"""

import os

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=8'
    ).strip()
os.environ.setdefault('TOKENIZERS_PARALLELISM', 'false')

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope='session')
def rng():
    return np.random.default_rng(0)
