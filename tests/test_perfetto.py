"""Perfetto/Chrome trace-event exporter tests (ISSUE 10 tentpole):
deterministic flight + span fixtures rendered to a structurally valid
trace (monotonic ts per track, complete X slices, registered categories),
round-tripped through ``dump_debug_bundle`` and the multi-host merge."""

from __future__ import annotations

import json

from distllm_tpu.observability import (
    FlightRecorder,
    dump_debug_bundle,
    merge_host_traces,
    to_trace_events,
    validate_trace_events,
)
from distllm_tpu.observability.instruments import TRACE_EVENT_CATEGORIES
from distllm_tpu.observability.perfetto import trace_time_origin


def _fixture_records() -> list[dict]:
    """A deterministic serving episode: prefill → two decode windows with
    a host gap between them → a preemption event → two finished requests
    (one carrying a propagated trace id)."""
    return [
        {'kind': 'prefill', 't_wall': 100.20, 'duration_s': 0.20,
         'batch': 2, 'tokens': 64, 'rids': [0, 1],
         'host_s': 0.01, 'put_s': 0.02, 'dispatch_s': 0.17},
        {'kind': 'decode', 't_wall': 100.50, 'duration_s': 0.25,
         'batch': 2, 'tokens': 32, 'mfu': 0.4, 'bw_util': 0.8},
        # 0.20 s host gap between this window's start (100.70) and the
        # previous window's end (100.50).
        {'kind': 'decode', 't_wall': 100.95, 'duration_s': 0.25,
         'batch': 2, 'tokens': 32},
        {'kind': 'preempt', 't_wall': 100.97, 'request_id': 1},
        {'kind': 'request', 't_wall': 100.98, 'request_id': 0,
         'trace_id': 'req-fixture', 'e2e_s': 0.9, 'ttft_s': 0.35,
         'queue_wait_s': 0.05, 'output_tokens': 17, 'prompt_tokens': 30},
        {'kind': 'request', 't_wall': 100.99, 'request_id': 1,
         'trace_id': None, 'e2e_s': 0.8, 'ttft_s': 0.4,
         'queue_wait_s': 0.1, 'output_tokens': 11, 'prompt_tokens': 34},
    ]


def _fixture_spans() -> list[dict]:
    return [
        {'name': 'chat-generate', 'wall_time_s': 100.05, 'duration_s': 0.95,
         'status': 'ok', 'span_id': 1, 'thread_id': 7,
         'attributes': {'request_id': 'req-fixture'}},
        {'name': 'chat-retrieve', 'wall_time_s': 100.01, 'duration_s': 0.03,
         'status': 'ok', 'span_id': 2, 'thread_id': 7, 'attributes': {}},
    ]


def _events(doc, **match):
    return [
        e for e in doc['traceEvents']
        if all(e.get(k) == v for k, v in match.items())
    ]


def test_exporter_structural_invariants():
    doc = to_trace_events(_fixture_records(), _fixture_spans())
    assert validate_trace_events(doc) == []
    # JSON round trip survives (what GET /debug/perfetto serves).
    reparsed = json.loads(json.dumps(doc))
    assert validate_trace_events(reparsed) == []
    assert reparsed['displayTimeUnit'] == 'ms'
    # ts is monotonic per (pid, tid) track — asserted independently of
    # the validator so a validator bug cannot mask a sort regression.
    per_track: dict = {}
    for event in reparsed['traceEvents']:
        if event['ph'] == 'M':
            continue
        per_track.setdefault((event['pid'], event.get('tid')), []).append(
            event['ts']
        )
    for track, stamps in per_track.items():
        assert stamps == sorted(stamps), track
    # Only X / i / M phases are emitted (complete slices, never torn B/E).
    assert {e['ph'] for e in reparsed['traceEvents']} <= {'X', 'i', 'M'}
    # Every non-metadata category is registered in the catalog.
    cats = {e['cat'] for e in reparsed['traceEvents'] if e['ph'] != 'M'}
    assert cats <= TRACE_EVENT_CATEGORIES


def test_exporter_tracks_and_host_gap():
    doc = to_trace_events(_fixture_records(), _fixture_spans())
    # One track per window kind actually present.
    prefill = _events(doc, cat='engine_step', name='prefill')
    decode = _events(doc, cat='engine_step', name='decode')
    assert len(prefill) == 1 and len(decode) == 2
    assert prefill[0]['tid'] != decode[0]['tid']
    assert decode[0]['tid'] == decode[1]['tid']
    # Flight fields survive as args (the attribution split included).
    assert prefill[0]['args']['host_s'] == 0.01
    assert decode[0]['args']['mfu'] == 0.4
    # Exactly the fixture's two idle gaps: prefill end (100.20) -> first
    # decode start (100.25), and first decode end (100.50) -> second
    # decode start (100.70).
    gaps = sorted(e['dur'] for e in _events(doc, cat='host_gap'))
    assert len(gaps) == 2
    assert abs(gaps[0] - 0.05e6) < 1.0 and abs(gaps[1] - 0.20e6) < 1.0
    # Preemption renders as an instant.
    assert _events(doc, cat='engine_event', name='preempt')[0]['ph'] == 'i'


def test_exporter_request_correlation():
    """The tentpole acceptance shape: a request-id-correlated track that
    spans server (span) -> engine (lifecycle slice + nested ttft)."""
    doc = to_trace_events(_fixture_records(), _fixture_spans())
    lifecycle = _events(doc, cat='request', name='req-fixture')
    assert len(lifecycle) == 1
    tid = lifecycle[0]['tid']
    # The server span carrying the same request id lands on that track.
    server_span = _events(doc, cat='span', name='chat-generate')
    assert server_span[0]['tid'] == tid
    # Nested ttft/queue_wait slices share the track and fit inside.
    ttft = [e for e in _events(doc, cat='request', name='ttft')
            if e['tid'] == tid]
    assert len(ttft) == 1
    assert ttft[0]['ts'] == lifecycle[0]['ts']
    assert ttft[0]['dur'] <= lifecycle[0]['dur']
    # The un-propagated request still gets a track, keyed by engine rid.
    assert _events(doc, cat='request', name='rid-1')
    # The request-less span goes to a per-thread track, not a request's.
    retrieve = _events(doc, cat='span', name='chat-retrieve')
    assert retrieve[0]['tid'] != tid


def test_exporter_skips_torn_and_unknown_records():
    records = _fixture_records() + [
        {'kind': 'mystery-kind', 't_wall': 101.0, 'duration_s': 0.1},
        {'kind': 'decode'},  # no t_wall (torn line)
        {'no_kind': True},
        {'kind': 'request', 't_wall': 101.0},  # pre-attribution: no e2e_s
    ]
    spans = _fixture_spans() + [{'name': 'open-span', 'wall_time_s': 100.0}]
    doc = to_trace_events(records, spans)
    assert validate_trace_events(doc) == []
    assert not _events(doc, name='mystery-kind')
    assert not _events(doc, name='open-span')


def test_debug_bundle_round_trip(tmp_path):
    """The dump_debug_bundle satellite: a real recorder's ring lands in
    the bundle as perfetto.json, parses, and validates."""
    recorder = FlightRecorder()
    for record in _fixture_records():
        fields = dict(record)
        recorder.record(fields.pop('kind'), **{
            k: v for k, v in fields.items() if k != 't_wall'
        })
    paths = dump_debug_bundle(
        tmp_path / 'bundle', reason='perfetto test', recorder=recorder
    )
    assert 'perfetto' in paths
    doc = json.loads((tmp_path / 'bundle' / 'perfetto.json').read_text())
    assert validate_trace_events(doc) == []
    names = {e['name'] for e in doc['traceEvents']}
    assert {'prefill', 'decode'} <= names


def test_merge_host_traces_per_host_groups():
    host_a = _fixture_records()
    host_b = [
        {'kind': 'decode', 't_wall': 100.40, 'duration_s': 0.3,
         'batch': 4, 'tokens': 64},
        {'kind': 'request', 't_wall': 100.70, 'request_id': 0,
         'e2e_s': 0.5, 'ttft_s': 0.2, 'output_tokens': 9},
    ]
    doc = merge_host_traces([
        ('host-a', host_a, _fixture_spans()),
        ('host-b', host_b, []),
    ])
    assert validate_trace_events(doc) == []
    pids = {e['pid'] for e in doc['traceEvents']}
    assert pids == {1, 2}
    process_names = {
        e['args']['name'] for e in doc['traceEvents']
        if e['ph'] == 'M' and e['name'] == 'process_name'
    }
    assert process_names == {'host-a', 'host-b'}
    # Shared time origin: host-b's decode starts 0.05 s after host-a's
    # earliest span (100.05), not at zero.
    b_decode = [
        e for e in doc['traceEvents']
        if e['pid'] == 2 and e.get('cat') == 'engine_step'
    ]
    origin = trace_time_origin(host_a, _fixture_spans())
    assert abs(b_decode[0]['ts'] - (100.40 - 0.3 - origin) * 1e6) < 1.0
