"""Host-RAM/disk KV tier for the prefix cache (ISSUE 14 tentpole):
HostKVTier LRU/byte-budget units, DiskKVTier round-trip + restart
persistence, spill→promote bit-exactness against never-evicted KV
(token identity with the tier on/off under greedy fp32), and PrefixCache
refcount invariants under cascaded eviction (docs/prefix_caching.md
"Tier hierarchy")."""

from __future__ import annotations

import numpy as np
import pytest

import jax

from distllm_tpu.generate.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distllm_tpu.generate.engine.kv_cache import (
    DiskKVTier,
    HostKVTier,
    block_digests,
)
from distllm_tpu.models import mistral


def _digest(i: int) -> bytes:
    return block_digests(list(range(i * 4 + 1, i * 4 + 5)), 4)[0]


def _block(i: int, nbytes: int = 256) -> tuple[np.ndarray, np.ndarray]:
    """One fake per-block KV pair of ``nbytes`` total (k + v)."""
    half = nbytes // 2
    k = np.full((half // 4,), i, np.float32)
    return k, k + 1


# ------------------------------------------------------------ host tier
def test_host_tier_lru_order_and_byte_budget():
    tier = HostKVTier(max_bytes=3 * 256)
    for i in range(3):
        assert tier.put(_digest(i), *_block(i))
    assert tier.num_blocks == 3 and tier.bytes_used == 3 * 256
    # get() refreshes LRU: 0 becomes most-recent, so inserting a fourth
    # block must evict 1 (the oldest untouched), never 0.
    k0, v0 = tier.get(_digest(0))
    assert k0[0] == 0 and v0[0] == 1
    tier.put(_digest(3), *_block(3))
    assert tier.bytes_used == 3 * 256  # budget enforced
    assert tier.get(_digest(1)) is None  # LRU victim
    assert tier.get(_digest(0)) is not None  # refreshed entry survived
    # Duplicate put: first writer wins, no double-counted bytes.
    assert not tier.put(_digest(0), *_block(9))
    assert tier.bytes_used == 3 * 256
    assert tier.get(_digest(0))[0][0] == 0


def test_host_tier_lookup_is_membership_only():
    tier = HostKVTier(max_bytes=2 * 256)
    tier.put(_digest(0), *_block(0))
    tier.put(_digest(1), *_block(1))
    # lookup must NOT refresh LRU (it runs in add_request's walk): after
    # looking 0 up, 0 is still the eviction victim.
    assert tier.lookup(_digest(0)) == 'host'
    assert tier.lookup(_digest(7)) is None
    tier.put(_digest(2), *_block(2))
    assert tier.get(_digest(0)) is None


# ------------------------------------------------------------ disk tier
def test_disk_tier_round_trip_and_budget(tmp_path):
    tier = DiskKVTier(tmp_path, max_bytes=1 << 20)
    k = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    v = k * 2
    assert tier.put(_digest(0), k, v)
    assert tier.contains(_digest(0))
    rk, rv = tier.get(_digest(0))
    assert rk.dtype == k.dtype and rk.shape == k.shape
    np.testing.assert_array_equal(rk, k)
    np.testing.assert_array_equal(rv, v)
    # bf16 KV round-trips byte-exactly through the raw-bytes format.
    import jax.numpy as jnp

    kb = np.asarray(jnp.arange(8, dtype=jnp.bfloat16).reshape(2, 4))
    assert tier.put(_digest(1), kb, kb)
    rb, _ = tier.get(_digest(1))
    assert rb.dtype == kb.dtype
    assert rb.tobytes() == kb.tobytes()
    # Byte budget: a tiny-budget tier keeps only the newest entries.
    # (Budget sized for ONE 256-byte block plus its v2 header.)
    small = DiskKVTier(tmp_path / 'small', max_bytes=340)
    small.put(_digest(2), *_block(2))
    small.put(_digest(3), *_block(3))
    assert not small.contains(_digest(2))
    assert small.contains(_digest(3))


def test_disk_tier_index_rebuilds_across_instances(tmp_path):
    a = DiskKVTier(tmp_path, max_bytes=1 << 20)
    a.put(_digest(0), *_block(0))
    a.put(_digest(1), *_block(1))
    b = DiskKVTier(tmp_path, max_bytes=1 << 20)  # fresh process stand-in
    assert b.num_blocks == 2
    assert b.get(_digest(0)) is not None


def test_host_tier_write_through_and_disk_fallback(tmp_path):
    disk = DiskKVTier(tmp_path, max_bytes=1 << 20)
    tier = HostKVTier(max_bytes=256, disk=disk)  # host holds ONE block
    tier.put(_digest(0), *_block(0))
    tier.put(_digest(1), *_block(1))  # evicts 0 from host; disk keeps it
    assert disk.num_blocks == 2  # write-through persisted both
    assert tier.lookup(_digest(0)) == 'disk'
    k0, _ = tier.get(_digest(0))  # disk hit re-enters the host pool
    assert k0[0] == 0


# ----------------------------------------------------------------- engine
def _tiny_engine(**cfg_kwargs):
    cfg = mistral.MistralConfig(
        vocab_size=64,
        hidden_size=32,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        intermediate_size=64,
        dtype='float32',
    )
    params = mistral.init(jax.random.PRNGKey(0), cfg)

    class IdTokenizer:
        eos_id = None

        def decode(self, ids):
            return ' '.join(str(i) for i in ids)

    engine = LLMEngine(
        cfg,
        params,
        IdTokenizer(),
        EngineConfig(
            block_size=4,
            prefer_native_allocator=False,
            enable_prefix_cache=True,
            **cfg_kwargs,
        ),
    )
    return cfg, params, engine


def _dense_greedy(cfg, params, prompt, n_tokens):
    ids = list(prompt)
    for _ in range(n_tokens):
        arr = np.asarray([ids], np.int32)
        hidden = mistral.apply(params, cfg, arr, np.ones_like(arr))
        lg = mistral.logits(params, cfg, hidden[:, -1])
        ids.append(int(np.argmax(np.asarray(lg)[0])))
    return ids[len(prompt):]


GREEDY = SamplingParams(temperature=0.0, max_tokens=4)
# 11-usable-block pool vs 24-token (6-block) prompts: every admission
# after the first evicts cached blocks — constant tier churn.
TIER_POOL = dict(num_blocks=12, max_num_seqs=2, max_model_len=48)
PROMPT_A = list(range(1, 25))
PROMPT_B = list(range(30, 54))


def test_spill_promote_round_trip_bit_exact():
    """Acceptance: a spilled-then-promoted prefix generates byte-identical
    tokens to the dense reference AND to a tier-off engine (greedy fp32),
    with >= 1 spill and >= 1 promotion actually recorded."""
    cfg, params, on = _tiny_engine(host_kv_tier_bytes=64 << 20, **TIER_POOL)
    _, _, off = _tiny_engine(**TIER_POOL)
    for prompt in (PROMPT_A, PROMPT_B, PROMPT_A):
        got_on = on.generate_ids([prompt], GREEDY)[0]
        got_off = off.generate_ids([prompt], GREEDY)[0]
        assert got_on == got_off == _dense_greedy(cfg, params, prompt, 4)
    # The B run evicted A's blocks into the tier; the second A promoted.
    assert on.tier_summary()['spilled_blocks'] > 0
    assert on._stats['tier_promotions'] >= 1
    assert on._stats['tier_promoted_blocks'] >= 1
    assert off.kv_tier is None


def test_refcount_invariants_under_cascaded_eviction():
    """free + cache-held == usable pool after a workload that spilled,
    promoted, and dropped through the cascade; host tier stays within
    budget. The no-leak twin of test_prefix_cache's eviction test."""
    cfg, params, engine = _tiny_engine(
        host_kv_tier_bytes=3 * 2 * 2 * 4 * 4 * 16 * 4,  # ~3 blocks
        **TIER_POOL,
    )
    rng = np.random.default_rng(3)
    for _ in range(8):
        prompt = list(rng.integers(1, 64, size=17))
        out = engine.generate_ids([prompt], GREEDY)[0]
        assert out == _dense_greedy(cfg, params, prompt, 4)
    usable = TIER_POOL['num_blocks'] - 1
    assert (
        engine.sched.num_free_blocks + engine.prefix_cache.num_cached
        == usable
    )
    assert engine.kv_tier.bytes_used <= engine.kv_tier.max_bytes
    assert engine.tier_summary()['spilled_blocks'] > 0


def test_disk_tier_persists_across_engine_restart(tmp_path):
    """Cold-start warm TTFT: a FRESH engine on the same digest chain
    promotes from the previous engine's disk spills and emits identical
    tokens."""
    cfg, params, first = _tiny_engine(
        host_kv_tier_bytes=64 << 20,
        disk_kv_tier_dir=str(tmp_path),
        **TIER_POOL,
    )
    want_a = _dense_greedy(cfg, params, PROMPT_A, 4)
    assert first.generate_ids([PROMPT_A], GREEDY)[0] == want_a
    # Force A's blocks through eviction so the spill reaches disk.
    first.generate_ids([PROMPT_B], GREEDY)
    assert first.kv_tier.disk.num_blocks > 0
    first.shutdown()

    _, _, fresh = _tiny_engine(
        host_kv_tier_bytes=64 << 20,
        disk_kv_tier_dir=str(tmp_path),
        **TIER_POOL,
    )
    assert fresh.generate_ids([PROMPT_A], GREEDY)[0] == want_a
    assert fresh._stats['tier_promotions'] >= 1
    assert fresh._stats.get('prefix_hit_tokens', 0) > 0


def test_promotion_survives_warmup_and_preemption_pressure():
    """The tier under the production serving-loop shape: warmup first
    (tier gather/scatter ladder compiles without state damage), then a
    preemption-heavy workload — outputs stay dense-exact."""
    cfg, params, engine = _tiny_engine(
        host_kv_tier_bytes=64 << 20,
        num_blocks=14,
        max_num_seqs=3,
        max_model_len=48,
        decode_steps=4,
        pipeline_depth=2,
    )
    engine.warmup()
    assert engine.sched.num_running == 0
    stem = list(range(1, 13))
    prompts = [stem + [20 + i] for i in range(3)] + [PROMPT_B[:9]]
    for _ in range(2):  # second pass re-arrives after eviction/spill
        outs = engine.generate_ids(prompts, GREEDY)
        for p, o in zip(prompts, outs):
            assert o == _dense_greedy(cfg, params, p, 4), p


def test_tier_config_validation():
    with pytest.raises(ValueError, match='enable_prefix_cache'):
        EngineConfig(host_kv_tier_bytes=1 << 20)
    with pytest.raises(ValueError, match='host_kv_tier_bytes'):
        EngineConfig(
            enable_prefix_cache=True, disk_kv_tier_dir='/tmp/x'
        )


# -------------------------------------------- gen_tier bench stage (smoke)
@pytest.mark.slow  # two engine warmups + two open-loop arms (~2 min); the
# fast tier covers the same contract in-process via the engine tests above
def test_gen_tier_stage_cpu_smoke(tmp_path):
    """Acceptance smoke: at a paged pool sized below the warm working
    set, the gen_tier fragment shows (1) warm-session TTFT with the tier
    on below the tier-off cold TTFT, (2) >= 1 recorded spill and >= 1
    promotion, and (3) tier on/off token identity under greedy fp32.
    Run directly: ``JAX_PLATFORMS=cpu DISTLLM_BENCH_SMALL=1 python
    bench.py --stage gen_tier``."""
    import json
    import os
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS='cpu',
        DISTLLM_BENCH_SMALL='1',
        DISTLLM_BENCH_RECORD_DIR=str(tmp_path),
        DISTLLM_BENCH_BUNDLE_DIR=str(tmp_path / 'bundles'),
        DISTLLM_BENCH_WATCHDOG_S='0',
    )
    proc = subprocess.run(
        [sys.executable, str(repo / 'bench.py'), '--stage', 'gen_tier'],
        capture_output=True, text=True, timeout=420, cwd=repo, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    fragment = json.loads(proc.stdout.strip().splitlines()[-1])
    assert 'gen_tier_error' not in fragment, fragment.get('gen_tier_error')
    assert fragment['gen_tier_tokens_identical'] is True
    assert fragment['gen_tier_spills'] >= 1
    assert fragment['gen_tier_promotions'] >= 1
    assert (
        fragment['gen_tier_warm_ttft_s'] < fragment['gen_tier_cold_ttft_s']
    )
    assert fragment['gen_tier_warm_ttft_speedup'] > 1.0
    assert 0.0 <= fragment['gen_tier_promotion_overlap'] <= 1.0


def test_tier_metrics_exported(tmp_path):
    from distllm_tpu.observability import render_prometheus

    _, _, engine = _tiny_engine(
        host_kv_tier_bytes=64 << 20,
        disk_kv_tier_dir=str(tmp_path),
        **TIER_POOL,
    )
    for prompt in (PROMPT_A, PROMPT_B, PROMPT_A):
        engine.generate_ids([prompt], GREEDY)
    text = render_prometheus()
    for series in (
        'distllm_prefix_tier_hits_total',
        'distllm_prefix_tier_misses_total',
        'distllm_prefix_tier_spills_total',
        'distllm_prefix_tier_promotions_total',
        'distllm_prefix_tier_bytes',
        'distllm_prefix_tier_evictions_total',
        'distllm_prefix_tier_dropped_blocks_total',
    ):
        assert series in text, series


# ------------------------------------------- resilience satellites (ISSUE 15)
def test_disk_tier_corrupt_kvblock_degrades_to_miss(tmp_path):
    """A corrupt or truncated .kvblock (bad header, short read) must count
    a distllm_prefix_tier_errors_total{tier="disk"}, drop the entry, and
    return None — never raise toward add_request."""
    from distllm_tpu.observability import instruments as _m

    tier = DiskKVTier(tmp_path, max_bytes=1 << 20)
    k = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    for i in range(3):
        assert tier.put(_digest(i), k + i, k * 2)

    def _file(i):
        return tmp_path / f'{_digest(i).hex()}.kvblock'

    # Three corruption classes: no header line at all, a header that is
    # not a shape/dtype record, and a body truncated mid-array.
    _file(0).write_bytes(b'garbage with no newline header')
    _file(1).write_bytes(b'{"not": "a shape record"}\n1234')
    payload = _file(2).read_bytes()
    _file(2).write_bytes(payload[: len(payload) // 2 + 7])

    errors_before = _m.PREFIX_TIER_ERRORS.labels(tier='disk').value
    for i in range(3):
        assert tier.get(_digest(i)) is None
    assert (
        _m.PREFIX_TIER_ERRORS.labels(tier='disk').value == errors_before + 3
    )
    # Entries dropped and corrupt files unlinked: the tier self-heals
    # instead of serving the same corruption forever.
    assert tier.num_blocks == 0
    assert not any(_file(i).exists() for i in range(3))
    # A healthy put/get cycle still works after the corruption storm.
    assert tier.put(_digest(3), k, k * 2)
    got_k, _ = tier.get(_digest(3))
    np.testing.assert_array_equal(got_k, k)


def test_corrupt_disk_tier_falls_through_to_cold_prefill(tmp_path):
    """Engine-level regression: every .kvblock corrupted behind the
    engine's back — add_request's tier walk plans promotions, the loads
    fail, and the requests cold-prefill to bit-exact tokens with the
    error counter as the only trace (never an exception)."""
    from distllm_tpu.observability import instruments as _m

    tier_dir = tmp_path / 'tier'
    # host_kv_tier_bytes=1: every spill is immediately evicted from the
    # host pool (write-through has already persisted it), so the DISK
    # tier is the only place warm prefixes survive — exactly the restart
    # topology the corruption must not break.
    cfg, params, engine = _tiny_engine(
        host_kv_tier_bytes=1, disk_kv_tier_dir=str(tier_dir), **TIER_POOL
    )
    first = engine.generate_ids([PROMPT_A], GREEDY)[0]
    engine.generate_ids([PROMPT_B], GREEDY)  # evicts A's blocks -> disk
    files = list(tier_dir.glob('*.kvblock'))
    assert files
    for path in files:
        path.write_bytes(b'corrupt')
    errors_before = _m.PREFIX_TIER_ERRORS.labels(tier='disk').value
    got = engine.generate_ids([PROMPT_A], GREEDY)[0]
    assert got == first == _dense_greedy(cfg, params, PROMPT_A, 4)
    assert _m.PREFIX_TIER_ERRORS.labels(tier='disk').value > errors_before
    assert not engine._stats.get('tier_promotions')


# --------------------------------- quantized int8 KV tier (docs/serving.md)
def test_disk_tier_v2_scales_round_trip(tmp_path):
    """A quantized spill (int8 data + fp32 per-block scales) round-trips
    byte-exactly through the v2 .kvblock layout — the body is sliced at
    exact header-derived offsets, never halved."""
    tier = DiskKVTier(tmp_path, max_bytes=1 << 20)
    rng = np.random.default_rng(0)
    k = rng.integers(-127, 128, size=(2, 4, 2, 8)).astype(np.int8)
    v = rng.integers(-127, 128, size=(2, 4, 2, 8)).astype(np.int8)
    ks = rng.uniform(0.01, 0.1, size=(2, 2)).astype(np.float32)
    vs = rng.uniform(0.01, 0.1, size=(2, 2)).astype(np.float32)
    assert tier.put(_digest(0), k, v, ks, vs)
    got = tier.get(_digest(0))
    assert len(got) == 4
    for a, b in zip(got, (k, v, ks, vs)):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert a.tobytes() == b.tobytes()
    # Restart: a fresh instance parses the same v2 files.
    fresh = DiskKVTier(tmp_path, max_bytes=1 << 20)
    assert len(fresh.get(_digest(0))) == 4


def test_disk_tier_versionless_kvblock_still_loads(tmp_path):
    """Pre-int8 spills (no ``version`` field, body = K bytes then V
    bytes) must keep loading on the legacy halve-the-body path — a repo
    upgrade must not cold-start every existing spill directory."""
    import json as _json

    tier = DiskKVTier(tmp_path, max_bytes=1 << 20)
    k = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    v = k * 2
    # Index the digest via a normal put, then rewrite the file in the
    # legacy layout behind the tier's back.
    assert tier.put(_digest(0), k, v)
    header = _json.dumps(
        {'shape': list(k.shape), 'dtype': str(k.dtype)}
    ).encode() + b'\n'
    path = tmp_path / f'{_digest(0).hex()}.kvblock'
    path.write_bytes(header + k.tobytes() + v.tobytes())
    rk, rv = tier.get(_digest(0))
    np.testing.assert_array_equal(rk, k)
    np.testing.assert_array_equal(rv, v)


def test_disk_tier_unknown_version_degrades_to_miss(tmp_path):
    """A .kvblock from a NEWER format (version 3) counts a
    distllm_prefix_tier_errors_total{tier="disk"}, drops the entry, and
    returns None — an old reader must cold-prefill, never hand the
    attention kernel another layout's bytes."""
    import json as _json

    from distllm_tpu.observability import instruments as _m

    tier = DiskKVTier(tmp_path, max_bytes=1 << 20)
    k = np.arange(8, dtype=np.float32)
    assert tier.put(_digest(0), k, k)
    header = _json.dumps(
        {'version': 3, 'shape': [8], 'dtype': 'float32'}
    ).encode() + b'\n'
    path = tmp_path / f'{_digest(0).hex()}.kvblock'
    path.write_bytes(header + k.tobytes() + k.tobytes())
    errors_before = _m.PREFIX_TIER_ERRORS.labels(tier='disk').value
    assert tier.get(_digest(0)) is None
    assert (
        _m.PREFIX_TIER_ERRORS.labels(tier='disk').value == errors_before + 1
    )
    assert tier.num_blocks == 0
    assert not path.exists()


def test_int8_spill_promote_round_trip_bit_exact():
    """The int8 pool's spill→promote loop is LOSSLESS: int8 data and
    fp32 scales ride the tiers as-is (no requantization), so a tier-on
    int8 engine must emit byte-identical tokens to a tier-off int8
    engine on the same eviction-churn workload."""
    _, _, on = _tiny_engine(
        host_kv_tier_bytes=64 << 20, kv_cache_dtype='int8', **TIER_POOL
    )
    _, _, off = _tiny_engine(kv_cache_dtype='int8', **TIER_POOL)
    assert on.kv.quantized and off.kv.quantized
    for prompt in (PROMPT_A, PROMPT_B, PROMPT_A):
        assert (
            on.generate_ids([prompt], GREEDY)[0]
            == off.generate_ids([prompt], GREEDY)[0]
        )
    assert on.tier_summary()['spilled_blocks'] > 0
    assert on._stats['tier_promotions'] >= 1


def test_int8_disk_warm_restart_promotes(tmp_path):
    """A fresh int8 engine over the previous process's spill directory
    promotes int8 blocks + scales from disk and reproduces the first
    engine's tokens — the v2 format carries everything promotion needs."""
    _, _, first = _tiny_engine(
        host_kv_tier_bytes=64 << 20,
        disk_kv_tier_dir=str(tmp_path),
        kv_cache_dtype='int8',
        **TIER_POOL,
    )
    want = first.generate_ids([PROMPT_A], GREEDY)[0]
    first.generate_ids([PROMPT_B], GREEDY)  # evict A's blocks -> disk
    assert first.kv_tier.disk.num_blocks > 0
    first.shutdown()

    _, _, fresh = _tiny_engine(
        host_kv_tier_bytes=64 << 20,
        disk_kv_tier_dir=str(tmp_path),
        kv_cache_dtype='int8',
        **TIER_POOL,
    )
    assert fresh.generate_ids([PROMPT_A], GREEDY)[0] == want
    assert fresh._stats['tier_promotions'] >= 1


def test_fp32_engine_over_int8_spills_cold_prefills(tmp_path):
    """Payload-arity defense: a full-precision engine meeting a
    quantized pool's 4-array spills must treat every one as a miss
    (tier_payload_mismatches), cold-prefill, and still emit dense-exact
    tokens — never scatter int8 bytes into an fp32 pool."""
    _, _, q = _tiny_engine(
        host_kv_tier_bytes=1,  # write-through then immediate host evict
        disk_kv_tier_dir=str(tmp_path),
        kv_cache_dtype='int8',
        **TIER_POOL,
    )
    q.generate_ids([PROMPT_A], GREEDY)
    q.generate_ids([PROMPT_B], GREEDY)
    assert q.kv_tier.disk.num_blocks > 0
    q.shutdown()

    cfg, params, fp = _tiny_engine(
        host_kv_tier_bytes=64 << 20,
        disk_kv_tier_dir=str(tmp_path),
        **TIER_POOL,
    )
    got = fp.generate_ids([PROMPT_A], GREEDY)[0]
    assert got == _dense_greedy(cfg, params, PROMPT_A, 4)
    assert fp._stats.get('tier_payload_mismatches', 0) >= 1
    assert not fp._stats.get('tier_promoted_blocks')


def test_disk_tier_warm_restart_bit_exact(tmp_path):
    """ISSUE 15 satellite: kill an engine mid-run, rebuild over the same
    disk_kv_tier_dir, and the fresh engine promotes the previous
    process's spills — warm prefix coverage and bit-exact tokens versus
    an unkilled run."""
    from distllm_tpu.observability import instruments as _m

    tier_dir = str(tmp_path / 'tier')
    kwargs = dict(
        host_kv_tier_bytes=64 << 20, disk_kv_tier_dir=tier_dir, **TIER_POOL
    )
    cfg, params, a = _tiny_engine(**kwargs)
    first = a.generate_ids([PROMPT_A], GREEDY)[0]
    # Kill mid-run: admit PROMPT_B (its admission pressure spills A's
    # cached blocks, write-through persisting them), take a couple of
    # engine steps, then abandon the process state with no graceful
    # flush — exactly what a SIGKILL leaves behind.
    a.add_request(list(PROMPT_B), GREEDY)
    a.step()
    a.step()
    a.shutdown()
    assert list((tmp_path / 'tier').glob('*.kvblock'))

    disk_promos_before = _m.PREFIX_TIER_PROMOTIONS.labels(
        tier='disk'
    ).value
    _, _, b = _tiny_engine(**kwargs)  # fresh process over the same dir
    got = b.generate_ids([PROMPT_A], GREEDY)[0]
    # Unkilled reference: same engine shape, fresh tier dir.
    _, _, ref = _tiny_engine(
        host_kv_tier_bytes=64 << 20,
        disk_kv_tier_dir=str(tmp_path / 'ref'),
        **TIER_POOL,
    )
    want = ref.generate_ids([PROMPT_A], GREEDY)[0]
    assert got == want == first == _dense_greedy(cfg, params, PROMPT_A, 4)
    # Warm restart is real: the rebuilt engine promoted spilled blocks
    # from disk (prefill covered cached tokens) instead of cold-running.
    assert b._stats.get('tier_promotions', 0) >= 1
    assert b._stats.get('prefix_hit_tokens', 0) > 0
    assert (
        _m.PREFIX_TIER_PROMOTIONS.labels(tier='disk').value
        > disk_promos_before
    )
