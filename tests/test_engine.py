"""Generation engine tests: paged attention, sampling, allocator, engine vs
dense-forward golden decoding."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distllm_tpu.generate.engine import (
    EngineConfig,
    LLMEngine,
    RequestState,
    SamplingParams,
)
from distllm_tpu.generate.engine.kv_cache import (
    NativeBlockAllocator,
    PagedKVCache,
    PyBlockAllocator,
)
from distllm_tpu.models import mistral
from distllm_tpu.ops.paged_attention import (
    paged_attention_xla,
    write_prefill_kv,
    write_token_kv,
)
from distllm_tpu.ops.sampling import sample_tokens


# ------------------------------------------------------------ paged attn
def _random_cache(rng, num_blocks=8, block_size=4, nkv=2, hd=8):
    k = rng.normal(size=(num_blocks, block_size, nkv, hd)).astype(np.float32)
    v = rng.normal(size=(num_blocks, block_size, nkv, hd)).astype(np.float32)
    return jnp.asarray(k), jnp.asarray(v)


def _dense_reference(q, k, v, context_len):
    """Plain attention over the first context_len tokens (GQA)."""
    num_heads, hd = q.shape
    nkv = k.shape[1]
    group = num_heads // nkv
    qg = q.reshape(nkv, group, hd)
    k = k[:context_len]
    v = v[:context_len]
    scores = np.einsum('kgd,tkd->kgt', qg, k) / np.sqrt(hd)
    probs = np.exp(scores - scores.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    return np.einsum('kgt,tkd->kgd', probs, v).reshape(num_heads, hd)


def test_paged_attention_matches_dense(rng):
    block_size = 4
    k_cache, v_cache = _random_cache(rng, block_size=block_size)
    # seq 0 uses blocks [2, 5] with 6 tokens; seq 1 uses [7] with 3 tokens.
    block_tables = jnp.asarray([[2, 5], [7, 0]], dtype=jnp.int32)
    context_lens = jnp.asarray([6, 3], dtype=jnp.int32)
    q = jnp.asarray(rng.normal(size=(2, 4, 8)).astype(np.float32))

    out = np.asarray(
        paged_attention_xla(q, k_cache, v_cache, block_tables, context_lens)
    )

    for seq, (blocks, ctx) in enumerate([((2, 5), 6), ((7,), 3)]):
        k_lin = np.concatenate([np.asarray(k_cache[b]) for b in blocks])
        v_lin = np.concatenate([np.asarray(v_cache[b]) for b in blocks])
        ref = _dense_reference(np.asarray(q[seq]), k_lin, v_lin, ctx)
        np.testing.assert_allclose(out[seq], ref, atol=1e-5, rtol=1e-4)


def test_paged_attention_pallas_interpret_matches_xla(rng):
    from distllm_tpu.ops.paged_attention import paged_attention_pallas

    k_cache, v_cache = _random_cache(rng, num_blocks=8, block_size=4)
    block_tables = jnp.asarray([[2, 5], [7, 0]], dtype=jnp.int32)
    context_lens = jnp.asarray([6, 3], dtype=jnp.int32)
    q = jnp.asarray(rng.normal(size=(2, 4, 8)).astype(np.float32))
    ref = np.asarray(
        paged_attention_xla(q, k_cache, v_cache, block_tables, context_lens)
    )
    out = np.asarray(
        paged_attention_pallas(
            q, k_cache, v_cache, block_tables, context_lens, interpret=True
        )
    )
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-4)


def test_write_token_and_prefill_kv(rng):
    k_cache = jnp.zeros((4, 4, 2, 3))
    v_cache = jnp.zeros((4, 4, 2, 3))
    # prefill 6 tokens into blocks [1, 2] (padded seq of 8)
    k_seq = jnp.asarray(rng.normal(size=(8, 2, 3)).astype(np.float32))
    v_seq = jnp.asarray(rng.normal(size=(8, 2, 3)).astype(np.float32))
    row = jnp.asarray([1, 2, 0, 0], dtype=jnp.int32)
    k_cache, v_cache = write_prefill_kv(
        k_cache, v_cache, k_seq, v_seq, row, jnp.int32(6)
    )
    np.testing.assert_allclose(np.asarray(k_cache[1]), np.asarray(k_seq[:4]))
    np.testing.assert_allclose(np.asarray(k_cache[2][:2]), np.asarray(k_seq[4:6]))
    # slot beyond length stays zero (trash block ate the padding)
    np.testing.assert_allclose(np.asarray(k_cache[2][2:]), 0.0)

    # token write at position 6 -> block row[6//4]=2, offset 2
    new_k = jnp.ones((1, 2, 3))
    new_v = jnp.ones((1, 2, 3)) * 2
    k_cache, v_cache = write_token_kv(
        k_cache, v_cache, new_k, new_v,
        jnp.asarray([[1, 2, 0, 0]], dtype=jnp.int32),
        jnp.asarray([6], dtype=jnp.int32),
    )
    np.testing.assert_allclose(np.asarray(k_cache[2][2]), 1.0)
    np.testing.assert_allclose(np.asarray(v_cache[2][2]), 2.0)


# -------------------------------------------------------------- sampling
def test_sampling_greedy():
    logits = jnp.asarray([[0.0, 5.0, 1.0], [3.0, 0.0, 0.1]])
    toks = sample_tokens(
        logits,
        jax.random.PRNGKey(0),
        temperature=jnp.zeros(2),
        top_p=jnp.ones(2),
        min_p=jnp.zeros(2),
    )
    assert list(np.asarray(toks)) == [1, 0]


def test_sampling_top_p_restricts_support():
    # One dominant token (p≈0.87); top_p=0.5 must always pick it.
    logits = jnp.tile(jnp.asarray([[4.0, 2.0, 0.0, -1.0]]), (64, 1))
    toks = sample_tokens(
        logits,
        jax.random.PRNGKey(1),
        temperature=jnp.ones(64),
        top_p=jnp.full(64, 0.5),
        min_p=jnp.zeros(64),
    )
    assert set(np.asarray(toks).tolist()) == {0}


def test_sampling_min_p_restricts_support():
    logits = jnp.tile(jnp.asarray([[4.0, 3.5, -8.0, -9.0]]), (128, 1))
    toks = np.asarray(
        sample_tokens(
            logits,
            jax.random.PRNGKey(2),
            temperature=jnp.ones(128),
            top_p=jnp.ones(128),
            min_p=jnp.full(128, 0.2),
        )
    )
    assert set(toks.tolist()) <= {0, 1}
    assert len(set(toks.tolist())) == 2  # still samples, not greedy


# -------------------------------------------------------------- allocator
@pytest.mark.parametrize('cls', [PyBlockAllocator, NativeBlockAllocator])
def test_block_allocator(cls):
    try:
        alloc = cls(8)
    except RuntimeError:
        pytest.skip('native toolchain unavailable')
    assert alloc.num_free == 7  # block 0 reserved
    blocks = [alloc.alloc() for _ in range(7)]
    assert 0 not in blocks
    assert alloc.alloc() == -1  # exhausted
    alloc.incref(blocks[0])
    alloc.free(blocks[0])
    assert alloc.num_free == 0  # still referenced
    alloc.free(blocks[0])
    assert alloc.num_free == 1
    with pytest.raises((AssertionError, ValueError)):
        alloc.free(blocks[0])  # double free


def test_paged_kv_cache_container():
    """Pure device-array container (block accounting lives in the scheduler)."""
    kv = PagedKVCache(
        num_layers=2, num_blocks=8, block_size=4, num_kv_heads=2,
        head_dim=4, dtype='float32',
    )
    assert kv.k.shape == (2, 8, 4, 2, 4)
    assert kv.blocks_needed(10) == 3
    assert kv.hbm_bytes == 2 * 2 * 8 * 4 * 2 * 4 * 4


# ----------------------------------------------------------------- engine
def _tiny_engine(num_blocks=64, max_num_seqs=4, max_model_len=64):
    cfg = mistral.MistralConfig(
        vocab_size=64,
        hidden_size=32,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        intermediate_size=64,
        dtype='float32',
    )
    params = mistral.init(jax.random.PRNGKey(0), cfg)

    class IdTokenizer:
        eos_id = None

        def decode(self, ids):
            return ' '.join(str(i) for i in ids)

    engine = LLMEngine(
        cfg,
        params,
        IdTokenizer(),
        EngineConfig(
            block_size=4,
            num_blocks=num_blocks,
            max_num_seqs=max_num_seqs,
            max_model_len=max_model_len,
            prefer_native_allocator=False,
        ),
    )
    return cfg, params, engine


def _dense_greedy_reference(cfg, params, prompt, n_tokens):
    """Greedy decoding via full dense re-forward each step (gold path)."""
    ids = list(prompt)
    for _ in range(n_tokens):
        arr = np.asarray([ids], np.int32)
        mask = np.ones_like(arr)
        hidden = mistral.apply(params, cfg, arr, mask)
        lg = mistral.logits(params, cfg, hidden[:, -1])
        ids.append(int(np.argmax(np.asarray(lg)[0])))
    return ids[len(prompt):]


def test_engine_greedy_matches_dense_forward():
    cfg, params, engine = _tiny_engine()
    prompts = [[5, 9, 12], [7, 3, 22, 31, 40, 2, 17], [1, 2, 3, 4, 5]]
    n = 8
    params_greedy = SamplingParams(temperature=0.0, max_tokens=n)
    outs = engine.generate_ids(prompts, params_greedy)
    for prompt, out in zip(prompts, outs):
        ref = _dense_greedy_reference(cfg, params, prompt, n)
        assert out == ref, f'{out} != {ref}'


def test_engine_batched_prefill_matches_dense_forward():
    """Many same-bucket prompts prefill in one padded dispatch; tokens must
    match the dense greedy reference exactly (padding rows are discarded,
    their K/V lands in the trash block)."""
    cfg, params, engine = _tiny_engine(num_blocks=128, max_num_seqs=8)
    rng = np.random.default_rng(3)
    # 6 prompts in the same 8-bucket + 3 in the 16-bucket: exercises a
    # full-8 pad, a partial pad, and cross-bucket grouping in one _admit.
    prompts = [list(rng.integers(1, 64, size=6)) for _ in range(6)]
    prompts += [list(rng.integers(1, 64, size=12)) for _ in range(3)]
    assert engine._prefill_batch_cap(8) >= 4
    outs = engine.generate_ids(prompts, SamplingParams(temperature=0.0, max_tokens=5))
    for prompt, out in zip(prompts, outs):
        assert out == _dense_greedy_reference(cfg, params, prompt, 5)


def test_engine_warmup_compiles_without_state_damage():
    """warmup() must not disturb scheduler state, the sampling RNG stream,
    or later generations."""
    cfg, params, engine = _tiny_engine()
    engine.warmup()
    assert engine.sched.num_running == 0
    assert engine.sched.num_free_blocks == 63  # all but trash block 0
    prompts = [[5, 9, 12], [7, 3, 22, 31]]
    outs = engine.generate_ids(prompts, SamplingParams(temperature=0.0, max_tokens=4))
    for prompt, out in zip(prompts, outs):
        assert out == _dense_greedy_reference(cfg, params, prompt, 4)
    # Seeded stochastic sampling reproduces between warmed/unwarmed engines
    # (keys are counter-derived per request, so warmup cannot advance any
    # sampling stream — docs/speculative.md "Sampled verification").
    _, _, warmed = _tiny_engine()
    warmed.warmup()
    _, _, fresh = _tiny_engine()
    sp = SamplingParams(temperature=0.9, max_tokens=6)
    assert warmed.generate_ids([[4, 2]], sp) == fresh.generate_ids([[4, 2]], sp)


def test_prefill_batch_cap_bounded_by_max_num_seqs():
    cfg, params, engine = _tiny_engine(max_num_seqs=3)
    engine.config.max_prefill_batch = 8
    # groups can never exceed 3 running slots -> pads to at most 4
    assert engine._prefill_batch_cap(8) == 4


def test_prefill_batch_cap_honors_token_budget():
    cfg, params, engine = _tiny_engine(max_num_seqs=8)
    engine.config.max_prefill_tokens = 64
    engine.config.max_prefill_batch = 8
    assert engine._prefill_batch_cap(8) == 8
    assert engine._prefill_batch_cap(16) == 4
    assert engine._prefill_batch_cap(64) == 1
    assert engine._prefill_batch_cap(128) == 1


def test_engine_continuous_batching_join_leave():
    """Requests with different lengths join/leave the batch mid-flight."""
    cfg, params, engine = _tiny_engine(max_num_seqs=2)
    sp_short = SamplingParams(temperature=0.0, max_tokens=2)
    sp_long = SamplingParams(temperature=0.0, max_tokens=6)
    r1 = engine.add_request([5, 6, 7], sp_long)
    r2 = engine.add_request([9, 8], sp_short)
    r3 = engine.add_request([11, 12, 13], sp_short)  # waits for a slot
    seen = {}
    while engine.has_unfinished:
        for rid, tok in engine.step():
            seen.setdefault(rid, []).append(tok)
    assert len(seen[r1]) == 6
    assert len(seen[r2]) == 2
    assert len(seen[r3]) == 2
    # all finished requests got their outputs recorded & slots/blocks freed
    assert engine.sched.num_running == 0
    ref = _dense_greedy_reference(cfg, params, [5, 6, 7], 6)
    assert seen[r1] == ref


def test_engine_preemption_under_block_pressure():
    """Tiny block pool forces recompute preemption; outputs still correct
    and complete (no tokens lost across preemption)."""
    # 7 usable blocks, 3 seqs needing 3 blocks each -> guaranteed pressure.
    cfg, params, engine = _tiny_engine(num_blocks=8, max_num_seqs=3, max_model_len=32)
    sp = SamplingParams(temperature=0.0, max_tokens=6)
    prompts = [[5, 9, 12, 4], [7, 3, 22, 31], [1, 2, 3, 4]]
    outs = engine.generate_ids(prompts, sp)
    for prompt, out in zip(prompts, outs):
        ref = _dense_greedy_reference(cfg, params, prompt, 6)
        assert out == ref
    # No block leaks: everything freed at the end.
    assert engine.sched.num_free_blocks == 7


def test_engine_prompt_at_max_model_len():
    """A prompt >= max_model_len truncates (keeping the tail) and still runs."""
    cfg, params, engine = _tiny_engine(num_blocks=64, max_model_len=16)
    sp = SamplingParams(temperature=0.0, max_tokens=2)
    prompt = list(range(1, 41))  # 40 tokens, max_model_len 16
    out = engine.generate_ids([prompt], sp)[0]
    ref = _dense_greedy_reference(cfg, params, prompt[-15:], 1)
    assert out[0] == ref[0]


def test_engine_unadmittable_prompt_raises():
    cfg, params, engine = _tiny_engine(num_blocks=4, max_model_len=32)
    with pytest.raises(ValueError, match='KV blocks'):
        engine.add_request(list(range(1, 30)))


def test_decode_sliding_window_matches_dense():
    """Sliding-window decode must equal dense forward with the window mask."""
    cfg = mistral.MistralConfig(
        vocab_size=64,
        hidden_size=32,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        intermediate_size=64,
        sliding_window=4,
        dtype='float32',
    )
    params = mistral.init(jax.random.PRNGKey(3), cfg)

    class IdTok:
        eos_id = None

        def decode(self, ids):
            return ''

    engine = LLMEngine(
        cfg, params, IdTok(),
        EngineConfig(
            block_size=4, num_blocks=32, max_num_seqs=2, max_model_len=32,
            prefer_native_allocator=False,
        ),
    )
    prompt = [5, 9, 12, 4, 7, 3]
    out = engine.generate_ids([prompt], SamplingParams(temperature=0.0, max_tokens=5))[0]
    ref = _dense_greedy_reference(cfg, params, prompt, 5)
    assert out == ref


def test_engine_stop_tokens():
    cfg, params, engine = _tiny_engine()
    ref = _dense_greedy_reference(cfg, params, [5, 9, 12], 8)
    stop = ref[3]
    sp = SamplingParams(temperature=0.0, max_tokens=20, stop_token_ids=(stop,))
    out = engine.generate_ids([[5, 9, 12]], sp)[0]
    assert out == ref[: ref.index(stop)]  # truncated at stop, token stripped


def test_engine_quantized_weights_generate():
    """Weight-only int8 serving (EngineConfig.quantization) runs the full
    prefill+decode path and mostly agrees with full-precision greedy."""
    cfg = mistral.MistralConfig(
        vocab_size=64,
        hidden_size=32,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        intermediate_size=64,
        dtype='float32',
    )
    params = mistral.init(jax.random.PRNGKey(0), cfg)

    class IdTokenizer:
        eos_id = None

        def decode(self, ids):
            return ' '.join(str(i) for i in ids)

    engine = LLMEngine(
        cfg,
        params,
        IdTokenizer(),
        EngineConfig(
            block_size=4,
            num_blocks=64,
            max_num_seqs=4,
            max_model_len=64,
            prefer_native_allocator=False,
            quantization='int8',
        ),
    )
    outs = engine.generate_ids(
        [[5, 9, 12]], SamplingParams(temperature=0.0, max_tokens=6)
    )
    assert len(outs[0]) == 6
    assert all(0 <= t < 64 for t in outs[0])


def test_engine_decode_steps_variants_match_dense():
    """K=1 (legacy per-token), K=4, and deep pipelining must all produce
    the dense greedy reference exactly — EOS overshoot tokens are
    discarded and budgets respected regardless of window shape."""
    prompts = [[5, 9, 12], [7, 3, 22, 31, 40, 2, 17]]
    n = 7  # deliberately not a multiple of any window size
    ref_cfg, ref_params, ref_engine = _tiny_engine()
    refs = [
        _dense_greedy_reference(ref_cfg, ref_params, p, n) for p in prompts
    ]
    for steps, depth in ((1, 1), (4, 1), (4, 3), (8, 2)):
        cfg = mistral.MistralConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
            num_kv_heads=2, intermediate_size=64, dtype='float32',
        )
        params = mistral.init(jax.random.PRNGKey(0), cfg)

        class IdTokenizer:
            eos_id = None

        engine = LLMEngine(
            cfg, params, IdTokenizer(),
            EngineConfig(
                block_size=4, num_blocks=64, max_num_seqs=4,
                max_model_len=64, prefer_native_allocator=False,
                decode_steps=steps, pipeline_depth=depth,
            ),
        )
        outs = engine.generate_ids(
            prompts, SamplingParams(temperature=0.0, max_tokens=n)
        )
        assert outs == refs, f'steps={steps} depth={depth}: {outs} != {refs}'


def test_engine_pipelined_preemption_pressure_matches_dense():
    """A pool too small for all sequences forces recompute preemption mid-
    pipeline; the drain-before-preempt rule must keep results exact."""
    cfg, params, engine = _tiny_engine(num_blocks=14, max_num_seqs=3)
    prompts = [[5, 9, 12], [7, 3, 22, 31], [1, 2, 3, 4, 5]]
    n = 6
    outs = engine.generate_ids(
        prompts, SamplingParams(temperature=0.0, max_tokens=n)
    )
    for prompt, out in zip(prompts, outs):
        assert out == _dense_greedy_reference(cfg, params, prompt, n)


def test_engine_max_tokens_below_window():
    """max_tokens=1 with decode_steps=8: the prefill emits the only token
    and the window machinery must not emit more."""
    cfg, params, engine = _tiny_engine()
    outs = engine.generate_ids(
        [[5, 9, 12]], SamplingParams(temperature=0.0, max_tokens=1)
    )
    assert len(outs[0]) == 1
    assert outs[0] == _dense_greedy_reference(cfg, params, [5, 9, 12], 1)


def test_sampling_windowed_matches_exact_when_cutoff_inside_window():
    """A peaky distribution's top-p cutoff falls inside the window, so the
    windowed fast path must keep the identical support; with the same key
    and identical filtered logits the sampled tokens agree exactly."""
    from distllm_tpu.ops.sampling import sample_tokens_windowed

    rng = np.random.default_rng(0)
    base = rng.normal(size=(32, 64)).astype(np.float32)
    base[:, :4] += 12.0  # concentrate ~all mass in 4 tokens
    logits = jnp.asarray(base)
    temp = jnp.full(32, 0.8)
    top_p = jnp.full(32, 0.9)
    min_p = jnp.zeros(32)
    # Exact same draws are not guaranteed (different categorical index
    # spaces), so compare supports over many keys.
    exact_set, win_set = set(), set()
    for i in range(40):
        k = jax.random.PRNGKey(i)
        exact_set.update(
            np.asarray(sample_tokens(logits, k, temp, top_p, min_p)).tolist()
        )
        win_set.update(
            np.asarray(
                sample_tokens_windowed(logits, k, temp, top_p, min_p, 8)
            ).tolist()
        )
    assert exact_set == win_set
    assert exact_set <= set(range(4))


def test_sampling_windowed_truncates_flat_distribution_to_window():
    from distllm_tpu.ops.sampling import sample_tokens_windowed

    logits = jnp.zeros((64, 128))  # uniform: top-p needs ~all tokens
    toks = np.asarray(
        sample_tokens_windowed(
            logits, jax.random.PRNGKey(0), jnp.ones(64),
            jnp.full(64, 0.99), jnp.zeros(64), 16,
        )
    )
    # All draws land in SOME 16-token window (ties make the exact ids
    # unspecified, but support size is capped).
    assert len(set(toks.tolist())) <= 16


def test_sampling_windowed_greedy_and_engine_path():
    from distllm_tpu.ops.sampling import sample_tokens_windowed

    logits = jnp.asarray([[0.0, 5.0, 1.0, -1.0], [3.0, 0.0, 0.1, 2.0]])
    toks = sample_tokens_windowed(
        logits, jax.random.PRNGKey(0), jnp.zeros(2), jnp.ones(2),
        jnp.zeros(2), 2,
    )
    assert list(np.asarray(toks)) == [1, 0]
    # top_window >= V must dispatch to the exact path unchanged.
    toks2 = sample_tokens(
        logits, jax.random.PRNGKey(0), jnp.zeros(2), jnp.ones(2),
        jnp.zeros(2), top_window=99,
    )
    assert list(np.asarray(toks2)) == [1, 0]


def test_engine_greedy_gemma2_matches_dense_forward():
    """The paged decode path (traced per-layer windows, softcaps, sandwich
    norms, (1+w) norms, scaled embeddings) serves gemma2 token-exactly vs
    the dense re-forward — long enough that decode positions pass the
    sliding window on the local (even) layers."""
    from distllm_tpu.models import gemma

    cfg = gemma.GemmaConfig(
        name='gemma2', vocab_size=64, hidden_size=32, num_layers=4,
        num_heads=4, num_kv_heads=2, head_dim=16, intermediate_size=64,
        max_position_embeddings=64, dtype='float32',
        activation='gelu_new', embedding_multiplier=32 ** 0.5,
        norm_plus_one=True, post_norms=True, query_scale=16 ** -0.5,
        attn_logit_softcap=50.0, final_logit_softcap=30.0,
        sliding_window=6, sliding_window_pattern='alternating',
        tie_word_embeddings=True, rms_norm_eps=1e-6,
    )
    params = gemma.init(jax.random.PRNGKey(1), cfg)

    class IdTokenizer:
        eos_id = None

        def decode(self, ids):
            return ' '.join(str(i) for i in ids)

    engine = LLMEngine(
        cfg, params, IdTokenizer(),
        EngineConfig(
            block_size=4, num_blocks=64, max_num_seqs=4, max_model_len=64,
            prefer_native_allocator=False,
        ),
    )
    prompts = [[5, 9, 12], [7, 3, 22, 31, 40, 2, 17]]
    n = 10  # prompt+decode crosses the window=6 boundary
    outs = engine.generate_ids(
        prompts, SamplingParams(temperature=0.0, max_tokens=n)
    )

    def dense_greedy(prompt):
        ids = list(prompt)
        for _ in range(n):
            arr = np.asarray([ids], np.int32)
            hidden = gemma.apply(params, cfg, arr, np.ones_like(arr))
            lg = gemma.logits(params, cfg, hidden[:, -1])
            ids.append(int(np.argmax(np.asarray(lg)[0])))
        return ids[len(prompt):]

    for prompt, out in zip(prompts, outs):
        ref = dense_greedy(prompt)
        assert out == ref, f'{out} != {ref}'


def test_engine_deferred_prefill_matches_dense_forward():
    # Opt-in pipelined prefill emission (EngineConfig.defer_prefill):
    # first tokens stay on device, scatter into the carried last-ids
    # vector, and are fetched one window late. Must stay token-exact vs
    # the dense reference, including continuous-batching slot reuse
    # (more prompts than slots) and a mid-stream finisher.
    cfg = mistral.MistralConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=64, dtype='float32',
    )
    params = mistral.init(jax.random.PRNGKey(0), cfg)

    class IdTokenizer:
        eos_id = None

    engine = LLMEngine(
        cfg, params, IdTokenizer(),
        EngineConfig(
            block_size=4, num_blocks=64, max_num_seqs=2, max_model_len=64,
            decode_steps=4, pipeline_depth=2, defer_prefill=True,
            prefer_native_allocator=False,
        ),
    )
    prompts = [[5, 9, 12], [7, 3, 22, 31, 40, 2, 17], [1, 2, 3, 4, 5],
               [44, 13], [9], [30, 31, 32, 33]]
    lens = [6, 9, 1, 8, 5, 7]  # mixed budgets incl. max_tokens=1
    rids = [
        engine.add_request(p, SamplingParams(temperature=0.0, max_tokens=n))
        for p, n in zip(prompts, lens)
    ]
    engine._run_to_completion()
    for p, n, rid in zip(prompts, lens, rids):
        got = engine._finished.pop(rid).output_ids
        ref = _dense_greedy_reference(cfg, params, p, n)
        assert got == ref, f'{got} != {ref}'


# ----------------------------------------- mixed prefill+decode windows
def test_ragged_paged_attention_decode_rows_match_decode_kernel(rng):
    """A ragged row with q_len=1 at position ctx-1 IS a decode row: the
    ragged path must agree with paged_attention_xla, with multi-token
    chunk rows coexisting in the same ragged batch."""
    from distllm_tpu.ops.paged_attention import ragged_paged_attention_xla

    block_size = 4
    k_cache, v_cache = _random_cache(rng, block_size=block_size)
    block_tables = jnp.asarray([[2, 5], [7, 3]], dtype=jnp.int32)
    context_lens = jnp.asarray([6, 5], dtype=jnp.int32)
    s = 3
    q = jnp.asarray(rng.normal(size=(2, s, 4, 8)).astype(np.float32))
    # Row 0: decode row — one valid query at its last position. Row 1: a
    # causal 3-token chunk span ending at position 4.
    q_positions = jnp.asarray([[5, 5, 5], [2, 3, 4]], dtype=jnp.int32)
    q_lens = jnp.asarray([1, 3], dtype=jnp.int32)
    out = np.asarray(
        ragged_paged_attention_xla(
            q, k_cache, v_cache, block_tables, context_lens, q_positions,
            q_lens=q_lens,
        )
    )
    dec = np.asarray(
        paged_attention_xla(
            q[:, 0], k_cache, v_cache, block_tables, context_lens
        )
    )
    np.testing.assert_allclose(out[0, 0], dec[0], atol=1e-5, rtol=1e-5)
    # Chunk row: each query vs a dense causal reference over its prefix.
    for j, pos in enumerate([2, 3, 4]):
        k_lin = np.concatenate(
            [np.asarray(k_cache[7]), np.asarray(k_cache[3])]
        )
        v_lin = np.concatenate(
            [np.asarray(v_cache[7]), np.asarray(v_cache[3])]
        )
        ref = _dense_reference(np.asarray(q[1, j]), k_lin, v_lin, pos + 1)
        np.testing.assert_allclose(out[1, j], ref, atol=1e-5, rtol=1e-4)
    # Padding queries (masked by q_lens) must stay finite.
    assert np.isfinite(out).all()


def _mixed_ab_engines(model_cfg, init_fn, seed=0, **cfg_kw):
    """Build (off, on) engines with identical weights for A/B runs."""
    class IdTokenizer:
        eos_id = None

    engines = []
    for mixed in (False, True):
        base = dict(
            block_size=4, num_blocks=96, max_num_seqs=2, max_model_len=96,
            decode_steps=4, pipeline_depth=2,
            prefer_native_allocator=False, enable_mixed_batching=mixed,
            max_window_prefill_tokens=8, max_window_prefill_seqs=2,
        )
        base.update(cfg_kw)
        engines.append(
            LLMEngine(
                model_cfg,
                init_fn(jax.random.PRNGKey(seed), model_cfg),
                IdTokenizer(),
                EngineConfig(**base),
            )
        )
    return engines


_STAGGER_PROMPT_LENS = (5, 21, 3, 33, 7, 13)
_STAGGER_OUT_LENS = (3, 17, 9, 5, 12, 8)


def _stagger_prompts(vocab, seed=1):
    """Staggered serving workload: more prompts than slots, unequal
    budgets (slots free mid-stream — the mixed-batching trigger), two
    prompts sharing a 2-block prefix (prefix-cache-hit tails ride), and
    long prompts whose tails chunk (chunk spans ride)."""
    rng = np.random.default_rng(seed)
    prompts = [
        list(rng.integers(1, vocab, size=n)) for n in _STAGGER_PROMPT_LENS
    ]
    shared = list(rng.integers(1, vocab, size=8))  # 2 full 4-blocks
    prompts[0] = shared + prompts[0]
    prompts[4] = shared + prompts[4]
    return prompts


def _run_stagger(engine, vocab, seed=1):
    prompts = _stagger_prompts(vocab, seed)
    rids = [
        engine.add_request(
            p, SamplingParams(temperature=0.0, max_tokens=n)
        )
        for p, n in zip(prompts, _STAGGER_OUT_LENS)
    ]
    engine._run_to_completion()
    return [engine._finished.pop(r).output_ids for r in rids]


@pytest.mark.slow
@pytest.mark.parametrize(
    'cache_kw',
    [
        {'enable_prefix_cache': True},
        {'enable_prefix_cache': True, 'prefill_chunk_tokens': 4},
        {'prefill_chunk_tokens': 4},
    ],
    ids=['prefix_cache', 'prefix_cache_chunked', 'chunked'],
)
def test_mixed_windows_token_identity(cache_kw):
    """Mixed on/off must emit bit-identical greedy tokens across prefix
    cache on/off and chunked tails, under pipelined (pipeline_depth=2)
    dispatch with mid-stream admissions — and wherever paged-route tails
    exist, the on run must actually fold them into windows (mixed
    records, fewer standalone dispatches). Only paged-route tails ride
    (cache-hit tails / chunked spans): fresh short prompts keep the
    batched dense prefill in BOTH arms, which is what makes identity a
    structural property rather than a cross-kernel numerics gamble."""
    cfg = mistral.MistralConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=64, dtype='float32',
    )
    off, on = _mixed_ab_engines(cfg, mistral.init, **cache_kw)
    assert _run_stagger(on, cfg.vocab_size) == _run_stagger(
        off, cfg.vocab_size
    )
    if cache_kw.get('enable_prefix_cache'):
        # Second pass over the same workload: pass 1 populated the prefix
        # cache, so these shared-prefix repeats are CACHE-HIT admissions —
        # the cached-tail ride path a single cold batch can never reach
        # (all add_requests land before anything prefills).
        assert _run_stagger(on, cfg.vocab_size) == _run_stagger(
            off, cfg.vocab_size
        )
    assert on._stats['mixed_windows'] > 0
    assert on._stats['mixed_prefill_tokens'] > 0
    assert (
        on._stats['prefill_dispatches'] < off._stats['prefill_dispatches']
    )


@pytest.mark.slow
def test_mixed_windows_token_identity_sliding_window():
    cfg = mistral.MistralConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=64, sliding_window=4,
        dtype='float32',
    )
    off, on = _mixed_ab_engines(
        cfg, mistral.init, prefill_chunk_tokens=4
    )
    outs_off = _run_stagger(off, cfg.vocab_size)
    outs_on = _run_stagger(on, cfg.vocab_size)
    assert outs_on == outs_off
    assert on._stats['mixed_windows'] > 0


@pytest.mark.slow
def test_mixed_windows_token_identity_gemma2():
    """gemma2-style serving (alternating windows, softcaps, sandwich
    norms, query_scale) through mixed windows stays token-exact."""
    from distllm_tpu.models import gemma

    cfg = gemma.GemmaConfig(
        name='gemma2', vocab_size=64, hidden_size=32, num_layers=4,
        num_heads=4, num_kv_heads=2, head_dim=16, intermediate_size=64,
        max_position_embeddings=128, dtype='float32',
        activation='gelu_new', embedding_multiplier=32 ** 0.5,
        norm_plus_one=True, post_norms=True, query_scale=16 ** -0.5,
        attn_logit_softcap=50.0, final_logit_softcap=30.0,
        sliding_window=6, sliding_window_pattern='alternating',
        tie_word_embeddings=True, rms_norm_eps=1e-6,
    )
    off, on = _mixed_ab_engines(
        cfg, gemma.init, seed=1, prefill_chunk_tokens=4
    )
    outs_off = _run_stagger(off, cfg.vocab_size)
    outs_on = _run_stagger(on, cfg.vocab_size)
    assert outs_on == outs_off
    assert on._stats['mixed_windows'] > 0


@pytest.mark.slow
def test_mixed_windows_match_dense_reference_and_preemption():
    """Mixed serving equals the dense greedy gold path even when a tiny
    pool forces recompute preemption of mid-prefill rows."""
    cfg = mistral.MistralConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=64, dtype='float32',
    )
    _, on = _mixed_ab_engines(
        cfg, mistral.init, num_blocks=20, max_num_seqs=3, max_model_len=64,
        prefill_chunk_tokens=4,
    )
    outs = _run_stagger(on, cfg.vocab_size)
    prompts = _stagger_prompts(cfg.vocab_size)
    # Dense gold references for the two longest-prompt requests (the ones
    # whose chunk rides + preemption interact); the full-matrix identity
    # tests above cover the rest without the dense re-forward cost.
    for i in (1, 3):
        ref = _dense_greedy_reference(
            cfg, on.params, prompts[i], _STAGGER_OUT_LENS[i]
        )
        assert outs[i] == ref
    assert all(
        len(o) == n for o, n in zip(outs, _STAGGER_OUT_LENS)
    )
    assert on.sched.num_free_blocks == 19  # no block leaks


@pytest.mark.slow
def test_mixed_windows_step_api_mid_stream_admission():
    """The synchronous step() path plans and processes mixed windows too;
    a request injected mid-decode rides them and its TTFT lifecycle
    fields are recorded."""
    cfg = mistral.MistralConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=64, dtype='float32',
    )
    _, on = _mixed_ab_engines(
        cfg, mistral.init, prefill_chunk_tokens=2
    )
    # Budgets staggered so r1's slot frees while r2 still decodes: the
    # injected r3 is then admitted MID-STREAM (equal budgets drain both
    # slots in the same window and the admission would land on an idle
    # engine, which bootstraps standalone by design).
    prompts = [[5, 9, 12], [7, 3, 22, 31], [1, 2, 3, 4, 5]]
    budgets = [3, 14, 8]
    r1 = on.add_request(
        prompts[0], SamplingParams(temperature=0.0, max_tokens=budgets[0])
    )
    r2 = on.add_request(
        prompts[1], SamplingParams(temperature=0.0, max_tokens=budgets[1])
    )
    seen: dict[int, list[int]] = {}
    r3 = None
    while on.has_unfinished:
        for rid, tok in on.step():
            seen.setdefault(rid, []).append(tok)
        if r3 is None and len(seen.get(r1, [])) >= budgets[0]:
            r3 = on.add_request(
                prompts[2],
                SamplingParams(temperature=0.0, max_tokens=budgets[2]),
            )
    for prompt, n, rid in zip(prompts, budgets, (r1, r2, r3)):
        assert seen[rid] == _dense_greedy_reference(
            cfg, on.params, prompt, n
        )
    assert on._stats['mixed_windows'] > 0
    assert on._finished[r3].t_first_token > 0.0


@pytest.mark.slow
def test_mixed_windows_warmup_compiles_without_state_damage():
    cfg = mistral.MistralConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=64, dtype='float32',
    )
    _, on = _mixed_ab_engines(
        cfg, mistral.init, prefill_chunk_tokens=4, max_model_len=32,
    )
    on.warmup()
    assert on.sched.num_running == 0
    assert on.sched.num_free_blocks == 95
    # Short post-warmup serve must still match the dense gold path
    # (scheduler state was untouched by warmup; sampling keys are
    # counter-derived per request, so there is no RNG state to damage).
    prompts = [[5, 9, 12], [7, 3, 22, 31, 40, 2, 17]]
    outs = on.generate_ids(
        prompts, SamplingParams(temperature=0.0, max_tokens=4)
    )
    for prompt, out in zip(prompts, outs):
        assert out == _dense_greedy_reference(cfg, on.params, prompt, 4)


def test_mixed_config_validation():
    with pytest.raises(ValueError, match='mutually exclusive'):
        EngineConfig(
            enable_mixed_batching=True, defer_prefill=True,
            prefill_chunk_tokens=16,
        )
    with pytest.raises(ValueError, match='max_window_prefill_tokens'):
        EngineConfig(
            enable_mixed_batching=True, max_window_prefill_tokens=0,
            prefill_chunk_tokens=16,
        )
    # Structurally inert combination: neither prefix cache nor chunking
    # means nothing can ever ride, yet warmup would compile the whole
    # mixed shape ladder — rejected at config time.
    with pytest.raises(ValueError, match='prefill_chunk_tokens'):
        EngineConfig(enable_mixed_batching=True)
    with pytest.raises(ValueError, match='>= 1'):
        EngineConfig(max_window_prefill_seqs=0)
    # defer_prefill alone stays a legal (tunnel-only) opt-in.
    assert EngineConfig(defer_prefill=True).defer_prefill


def test_mixed_windows_token_identity_fast_canary():
    """Fast-tier mixed on/off identity canary: chunked + prefix-cache
    config, staggered budgets, pipelined dispatch. The full matrix
    (cache on/off, sliding-window, gemma2, preemption, step API, warmup)
    runs in the slow tier — this keeps one end-to-end identity + fold
    assertion inside the 870 s tier-1 budget."""
    cfg = mistral.MistralConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=64, dtype='float32',
    )
    off, on = _mixed_ab_engines(
        cfg, mistral.init, enable_prefix_cache=True,
        prefill_chunk_tokens=4,
    )
    prompts = _stagger_prompts(cfg.vocab_size)
    budgets = (2, 9, 4, 3, 6, 4)

    def run(engine):
        rids = [
            engine.add_request(
                p, SamplingParams(temperature=0.0, max_tokens=n)
            )
            for p, n in zip(prompts, budgets)
        ]
        engine._run_to_completion()
        return [engine._finished.pop(r).output_ids for r in rids]

    assert run(on) == run(off)
    assert on._stats['mixed_windows'] > 0
    assert (
        on._stats['prefill_dispatches'] < off._stats['prefill_dispatches']
    )


def test_mixed_flight_records_and_metrics():
    """Chunk-carrying windows record kind='mixed' with prefill payload
    fields, and the distllm_engine_mixed_* series advance."""
    from distllm_tpu.observability import instruments as metrics
    from distllm_tpu.observability.flight import get_flight_recorder

    cfg = mistral.MistralConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=64, dtype='float32',
    )
    _, on = _mixed_ab_engines(
        cfg, mistral.init, prefill_chunk_tokens=4
    )
    before = len(
        [r for r in get_flight_recorder().snapshot() if r['kind'] == 'mixed']
    )
    windows_before = metrics.MIXED_WINDOWS.value
    tokens_before = metrics.MIXED_PREFILL_TOKENS.value
    _run_stagger(on, cfg.vocab_size)
    mixed_records = [
        r for r in get_flight_recorder().snapshot() if r['kind'] == 'mixed'
    ]
    assert len(mixed_records) > before
    rec = mixed_records[-1]
    assert rec['prefill_tokens'] > 0
    assert rec['prefill_rows'] >= 1
    assert metrics.MIXED_WINDOWS.value > windows_before
    assert metrics.MIXED_PREFILL_TOKENS.value > tokens_before


def test_mixed_exception_recovery_rolls_back_inflight_chunk_spans(
    monkeypatch,
):
    """A chunk span whose window is lost to an exception mid-drain must
    roll ``prefill_sent`` back to ``prefill_done`` so the span re-rides
    after a catch-and-continue resume — otherwise the planner skips the
    request as 'in flight' forever and the serving loop livelocks."""
    cfg = mistral.MistralConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=64, dtype='float32',
    )
    _, on = _mixed_ab_engines(cfg, mistral.init, prefill_chunk_tokens=2)
    sp = SamplingParams(temperature=0.0, max_tokens=6)
    # Bootstrap one decoding request so the second one's tail rides.
    r1 = on.add_request([5, 9, 12], sp)
    while not on._requests[r1].output_ids:
        on.step()
    r2 = on.add_request([7, 3, 22, 31, 40], sp)

    armed = {'on': True}
    orig = LLMEngine._process_window

    def boom(self, window):
        if armed['on'] and window.get('chunk_plan'):
            armed['on'] = False  # lose exactly one chunk-carrying window
            raise RuntimeError('injected mid-drain')
        return orig(self, window)

    monkeypatch.setattr(LLMEngine, '_process_window', boom)
    with pytest.raises(RuntimeError, match='injected'):
        on._run_to_completion()
    req2 = on._requests[r2]
    assert req2.state is RequestState.RUNNING
    assert req2.prefill_sent == req2.prefill_done  # rolled back
    # The planner re-plans the dropped span instead of skipping it.
    assert any(
        request.request_id == r2
        for request, _, _ in on._plan_window_chunks()
    )
