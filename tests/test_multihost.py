"""Two-process jax.distributed smoke tests (the pod DCN init path).

Reference parity: ``distllm/parsl.py:172-252`` — the reference trusts
Parsl HTEX to stitch nodes together; here the equivalent trust boundary is
``jax.distributed.initialize`` joining per-host processes into one global
device view, exercised with two REAL processes on the CPU backend (Gloo
collectives) exactly the way the rendered PBS/Slurm pod scripts drive it:
topology via ``DISTLLM_JAX_*`` env vars, rank via the scheduler-rank
fallback (``parallel/multihost.py``).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def _cpu_env(**extra: str) -> dict[str, str]:
    env = dict(os.environ)
    # Belt and suspenders vs the axon sitecustomize (see tests/conftest.py):
    # the env var alone loses to sitecustomize's config pin, and a TPU
    # grab here would hang the suite when the tunnel is wedged.
    env['JAX_PLATFORMS'] = 'cpu'
    env.pop('XLA_FLAGS', None)
    env.update(extra)
    return env


_SPMD_DRIVER = textwrap.dedent(
    """
    import os, sys
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from distllm_tpu.parallel.multihost import init_multihost, process_rank

    out_path = sys.argv[1]
    # Topology comes ONLY from the DISTLLM_JAX_* / scheduler-rank env,
    # like a rendered pod script.
    rank, size = init_multihost()
    assert (rank, size) == process_rank()
    assert size == 2, size

    devices = np.array(jax.devices())  # global view: one CPU per process
    assert devices.size == 2, devices
    mesh = Mesh(devices, ('data',))

    # Sharded forward: data-parallel batch, replicated weights — the same
    # layout the embed pipeline uses on a pod. Deterministic inputs so the
    # parent can recompute single-process.
    batch, dim, hidden = 4, 8, 16
    x = np.arange(batch * dim, dtype=np.float32).reshape(batch, dim) / 10
    w1 = np.sin(np.arange(dim * hidden, dtype=np.float32)).reshape(dim, hidden)
    w2 = np.cos(np.arange(hidden * dim, dtype=np.float32)).reshape(hidden, dim)

    from jax.experimental import multihost_utils

    local = x[rank * (batch // 2) : (rank + 1) * (batch // 2)]
    gx = multihost_utils.host_local_array_to_global_array(
        local, mesh, P('data')
    )

    @jax.jit
    def forward(x, w1, w2):
        return jax.nn.gelu(x @ w1) @ w2

    y = jax.jit(
        forward, out_shardings=NamedSharding(mesh, P())
    )(gx, w1, w2)  # replicated output -> every process holds the full batch
    np.save(out_path, np.asarray(y))
    """
)


def test_two_process_sharded_forward_matches_single(tmp_path):
    port = _free_port()
    procs = []
    for rank in range(2):
        out = tmp_path / f'rank{rank}.npy'
        env = _cpu_env(
            DISTLLM_JAX_COORDINATOR=f'127.0.0.1:{port}',
            DISTLLM_JAX_NUM_PROCESSES='2',
            # Rank arrives via the scheduler-rank fallback chain, the way
            # srun/mpiexec deliver it (SLURM_PROCID on Slurm pods).
            SLURM_PROCID=str(rank),
        )
        procs.append(
            (
                subprocess.Popen(
                    [sys.executable, '-c', _SPMD_DRIVER, str(out)],
                    env=env,
                    cwd=REPO,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                ),
                out,
            )
        )
    try:
        for proc, _ in procs:
            stdout, _ = proc.communicate(timeout=180)
            assert proc.returncode == 0, stdout[-2000:]
    finally:
        # A timeout/assert must not LEAK the other rank: an orphaned
        # Gloo-barrier process spins at 100% CPU forever and starves
        # every test after this one (measured: the tier-1 run burned its
        # whole remaining budget here on a 1-core box).
        for proc, _ in procs:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()

    # Single-process reference on this process's CPU backend.
    import jax

    x = np.arange(4 * 8, dtype=np.float32).reshape(4, 8) / 10
    w1 = np.sin(np.arange(8 * 16, dtype=np.float32)).reshape(8, 16)
    w2 = np.cos(np.arange(16 * 8, dtype=np.float32)).reshape(16, 8)
    expected = np.asarray(jax.nn.gelu(x @ w1) @ w2)

    for _, out in procs:
        got = np.load(out)
        np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5)


def test_pod_worker_joins_jax_runtime(tmp_path):
    """The worker CLI's --jax-distributed flag end-to-end: a real fabric
    coordinator plus one worker process that joins the (size-1) global JAX
    runtime before serving, then completes a task that reads the runtime."""
    from distllm_tpu.parallel.fabric import Coordinator, ZmqPoolExecutor

    coordinator = Coordinator(bind='tcp://*:0', advertise_host='127.0.0.1')
    jax_port = _free_port()
    env = _cpu_env(
        DISTLLM_JAX_COORDINATOR=f'127.0.0.1:{jax_port}',
        DISTLLM_JAX_NUM_PROCESSES='1',
        DISTLLM_JAX_PROCESS_ID='0',
        # The pickled task fn lives in this test module; workers resolve
        # it by import path, same as Parsl's module-level-fn rule.
        PYTHONPATH=str(REPO / 'tests'),
    )
    proc = subprocess.Popen(
        [
            sys.executable,
            '-m',
            'distllm_tpu.parallel.worker',
            '--coordinator',
            coordinator.endpoint,
            '--jax-distributed',
        ],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        executor = ZmqPoolExecutor(coordinator)
        # map() blocks forever if the worker dies before joining (e.g. a
        # jax.distributed incompatibility) — bound it so a wedged worker
        # costs one failed test, not the whole remaining tier-1 budget
        # (measured on a 1-core box: this line ate every test after it).
        import threading

        result_box: dict = {}
        mapper = threading.Thread(
            target=lambda: result_box.update(
                r=executor.map(_report_runtime, [0])
            ),
            daemon=True,
        )
        mapper.start()
        mapper.join(timeout=150)
        assert 'r' in result_box, (
            'worker never completed the task (map wedged); worker log:\n'
            + (proc.stdout.read()[-2000:] if proc.poll() is not None else
               '<worker still running>')
        )
        results = result_box['r']
        assert results == [(0, 1)]
        # Graceful teardown MUST work without signals: a worker in the
        # global JAX runtime swallows SIGTERM (preemption notifier), so
        # the poison pill is the only clean exit on a pod.
        executor.shutdown()
        stdout, _ = proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            stdout, _ = proc.communicate(timeout=30)
    assert proc.returncode == 0, stdout[-2000:]
    assert 'jax runtime rank 0/1' in stdout, stdout[-2000:]


def _report_runtime(_item):
    from distllm_tpu.parallel.multihost import process_rank

    return process_rank()
