"""Engine tensor parallelism on the virtual CPU mesh.

The reference delegates TP to vLLM (``tensor_parallel_size`` passthrough,
``distllm/generate/generators/vllm_backend.py:66-67``); here TP is a mesh
axis and the whole serving path — prefill, paged KV scatter, decode gather,
sampling — must produce the SAME tokens under GSPMD propagation as on one
device. Greedy decoding makes equality exact.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from distllm_tpu.generate.engine.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distllm_tpu.models import mistral
from distllm_tpu.parallel.mesh import MeshSpec, make_mesh
from distllm_tpu.parallel.sharding import shard_pytree


class _Tok:
    eos_id = None


@pytest.fixture(scope='module')
def model():
    cfg = mistral.MistralConfig(
        vocab_size=256,
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        intermediate_size=128,
        dtype='float32',
    )
    params = mistral.init(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _generate(cfg, params, mesh, prompts, max_tokens=12):
    engine_cfg = EngineConfig(
        block_size=4,
        num_blocks=64,
        max_num_seqs=4,
        max_model_len=128,
        prefill_min_bucket=8,
    )
    if mesh is not None:
        params = shard_pytree(params, mistral.param_specs(cfg, params), mesh)
    engine = LLMEngine(cfg, params, _Tok(), engine_cfg, mesh=mesh)
    outs = engine.generate_ids(
        prompts, SamplingParams(temperature=0.0, max_tokens=max_tokens)
    )
    engine.shutdown()
    return outs


def test_tp2_matches_single_device(model):
    cfg, params = model
    rng = np.random.default_rng(0)
    prompts = [
        list(rng.integers(1, cfg.vocab_size, size=n)) for n in (5, 17, 9, 26)
    ]

    single = _generate(cfg, params, None, prompts)
    mesh = make_mesh(MeshSpec(data=1, model=2), devices=jax.devices()[:2])
    tp = _generate(cfg, params, mesh, prompts)

    assert all(len(o) == 12 for o in single)
    assert single == tp


def test_tp4_matches_single_device(model):
    # num_kv_heads=2 < tp=4 must be rejected, not silently wrong.
    cfg, params = model
    mesh = make_mesh(MeshSpec(data=1, model=4), devices=jax.devices()[:4])
    with pytest.raises(ValueError, match='num_kv_heads'):
        _generate(cfg, params, mesh, [[1, 2, 3]])


def test_tp2_qwen2_biases_match_single_device():
    """Q/K/V biases (Qwen2 family) shard with their column-parallel
    kernels — the bias specs must keep TP token-exact, not just run."""
    cfg = mistral.MistralConfig(
        vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=128, attention_bias=True,
        dtype='float32',
    )
    params = mistral.init(jax.random.PRNGKey(3), cfg)
    assert 'bias' in params['layers']['q']
    rng = np.random.default_rng(2)
    prompts = [
        list(rng.integers(1, cfg.vocab_size, size=n)) for n in (5, 18, 9)
    ]
    single = _generate(cfg, params, None, prompts)
    mesh = make_mesh(MeshSpec(data=1, model=2), devices=jax.devices()[:2])
    tp = _generate(cfg, params, mesh, prompts)
    assert single == tp


def test_tp2_with_continuous_batching_churn(model):
    """Requests joining/leaving the batch (staggered finishes) under TP."""
    cfg, params = model
    rng = np.random.default_rng(1)
    prompts = [
        list(rng.integers(1, cfg.vocab_size, size=n))
        for n in (3, 30, 7, 21, 12, 5)
    ]

    single = _generate(cfg, params, None, prompts, max_tokens=8)
    mesh = make_mesh(MeshSpec(data=1, model=2), devices=jax.devices()[:2])
    tp = _generate(cfg, params, mesh, prompts, max_tokens=8)

    assert single == tp


def test_tp2_gemma2_matches_single_device():
    """Gemma-2's extras (sandwich norms, softcaps, scaled embeddings,
    alternating windows) must stay token-exact under TP — the post norms
    are replicated and softcapping is elementwise on already-combined
    scores, so TP=2 greedy output must equal single-device."""
    from distllm_tpu.models import gemma

    cfg = gemma.GemmaConfig(
        name='gemma2', vocab_size=256, hidden_size=64, num_layers=4,
        num_heads=4, num_kv_heads=2, head_dim=16, intermediate_size=128,
        max_position_embeddings=128, dtype='float32',
        activation='gelu_new', embedding_multiplier=64 ** 0.5,
        norm_plus_one=True, post_norms=True, query_scale=16 ** -0.5,
        attn_logit_softcap=50.0, final_logit_softcap=30.0,
        sliding_window=6, sliding_window_pattern='alternating',
        tie_word_embeddings=True, rms_norm_eps=1e-6,
    )
    params = gemma.init(jax.random.PRNGKey(5), cfg)
    assert 'post_attn_ln' in params['layers']
    rng = np.random.default_rng(4)
    prompts = [
        list(rng.integers(1, cfg.vocab_size, size=n)) for n in (5, 18, 9)
    ]
    single = _generate(cfg, params, None, prompts)
    mesh = make_mesh(MeshSpec(data=1, model=2), devices=jax.devices()[:2])
    tp = _generate(cfg, params, mesh, prompts)
    assert single == tp
