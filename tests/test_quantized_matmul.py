"""int8 matmul tiers: pallas-interpret == xla == dequantize reference.

The serving claim under test: ``int8_dense`` computes the same thing as
``x @ QTensor.dequantize()`` while never materializing a float weight —
the property that fixed the 6x-off-floor int8 decode windows
(chipback_r05/bench_run1.json, ops/quantized_matmul.py docstring).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from distllm_tpu.models import common
from distllm_tpu.ops import quantized_matmul as qmm
from distllm_tpu.ops.quantization import quantize_int8


def _case(m, k, n, seed=0, dtype=jnp.bfloat16):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(
        rng.standard_normal((m, k)).astype(np.float32), dtype=dtype
    )
    w = rng.standard_normal((k, n)).astype(np.float32) * 0.05
    qt = quantize_int8(w, out_dtype='bfloat16')
    ref = np.asarray(
        jnp.asarray(x, jnp.float32) @ jnp.asarray(qt.dequantize(), jnp.float32)
    )
    return x, qt, ref


def _assert_close(out, ref):
    np.testing.assert_allclose(
        np.asarray(out, np.float32), ref, rtol=0.05, atol=0.05
    )


def test_xla_tier_matches_dequantize():
    x, qt, ref = _case(8, 512, 256)
    _assert_close(qmm.int8_matmul_xla(x, qt.q, qt.scale), ref)


def test_pallas_interpret_matches_xla():
    x, qt, _ = _case(32, 512, 256)
    got = qmm.int8_matmul_pallas(x, qt.q, qt.scale, interpret=True)
    want = qmm.int8_matmul_xla(x, qt.q, qt.scale)
    np.testing.assert_allclose(
        np.asarray(got, np.float32),
        np.asarray(want, np.float32),
        rtol=0.02,
        atol=0.02,
    )


def test_pallas_row_padding():
    # M=5 pads to the 16-row sublane tile; padded rows must not leak.
    x, qt, ref = _case(5, 512, 256)
    got = qmm.int8_matmul_pallas(x, qt.q, qt.scale, interpret=True)
    assert got.shape == (5, 256)
    _assert_close(got, ref)


def test_int8_dense_leading_dims():
    x, qt, ref = _case(6, 512, 256)
    x3 = x.reshape(2, 3, 512)
    got = qmm.int8_dense(x3, qt.q, qt.scale, backend='xla')
    assert got.shape == (2, 3, 256)
    _assert_close(got.reshape(6, 256), ref)


def test_int8_dense_interpret_backend():
    x, qt, ref = _case(4, 512, 128)
    _assert_close(qmm.int8_dense(x, qt.q, qt.scale, backend='interpret'), ref)


@pytest.mark.parametrize(
    'm,k,n,ok',
    [
        (8, 512, 384, True),  # 384 = 3*128: a valid tile exists
        (8, 300, 256, False),  # K has no 128-multiple tile
        (8, 512, 200, False),  # N has no 128-multiple tile
        (qmm.MAX_PALLAS_ROWS + 1, 512, 256, False),  # prefill-sized M
    ],
)
def test_tile_contract(m, k, n, ok):
    assert qmm.pallas_supported(m, k, n) is ok


def test_unknown_backend_rejected():
    x, qt, _ = _case(4, 512, 128)
    with pytest.raises(ValueError, match='unknown quantized-matmul'):
        qmm.int8_dense(x, qt.q, qt.scale, backend='Pallas')


def test_common_dense_routes_int8():
    # dense() must dispatch 2-D int8 QTensors to int8_dense (no float
    # weight), honoring the process tier, and still apply bias.
    qmm.set_default_backend('interpret')
    try:
        x, qt, ref = _case(4, 512, 256)
        bias = jnp.asarray(np.linspace(-1, 1, 256), jnp.bfloat16)
        got = common.dense(x, qt, bias)
    finally:
        qmm.set_default_backend('auto')
    _assert_close(got, ref + np.asarray(bias, np.float32))


def test_set_default_backend_validates():
    with pytest.raises(ValueError):
        qmm.set_default_backend('cuda')
    assert qmm.default_backend() == 'auto'


def test_common_dense_nf4_still_dequantizes():
    from distllm_tpu.ops.quantization import quantize_nf4

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((4, 256)), jnp.bfloat16)
    w = rng.standard_normal((256, 128)).astype(np.float32) * 0.05
    qt = quantize_nf4(w, 64, 'bfloat16')
    ref = np.asarray(
        jnp.asarray(x, jnp.float32) @ jnp.asarray(qt.dequantize(), jnp.float32)
    )
    _assert_close(common.dense(x, qt), ref)
