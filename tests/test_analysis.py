"""distlint framework tests: per-rule fixtures + end-to-end self-run.

Every rule gets the four-fixture treatment — a violating snippet, a
clean snippet, a suppressed snippet, and an unused-suppression snippet —
driven through the real driver (:func:`analyze`) on virtual
:class:`SourceFile`\\ s, so suppression application and path scoping are
exercised exactly as in production. The end-to-end tests assert the
repo itself is clean, the CLI exit codes, and the stability of the JSON
output schema.
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path
from textwrap import dedent

from distllm_tpu.analysis import (
    RULES,
    Project,
    SourceFile,
    analyze,
    build_report,
)
from distllm_tpu.analysis.core import (
    SUPPRESSION_UNJUSTIFIED,
    SUPPRESSION_UNKNOWN_RULE,
    SUPPRESSION_UNUSED,
    SYNTAX_ERROR,
)
from distllm_tpu.analysis.rules_tpu import TracedIndex

REPO = Path(__file__).resolve().parent.parent
FIXTURE_REL = 'distllm_tpu/_fixture.py'

# A minimal instruments.py stand-in so catalog rules resolve against a
# known catalog instead of the live one.
FAKE_INSTRUMENTS = (
    "REG = None\n"
    "C = REG.counter('distllm_good_total', 'help')\n"
    "FLIGHT_KINDS = frozenset({'decode', 'prefill'})\n"
    "TRACE_EVENT_CATEGORIES = frozenset({'engine'})\n"
    "COMPILE_PHASES = frozenset({'warmup'})\n"
)


def run_rules(
    text: str,
    rule_ids,
    rel: str = FIXTURE_REL,
    *,
    audit: bool = False,
):
    """Analyze one virtual file (plus the fake catalog) with a rule
    subset; returns the diagnostics anchored to the virtual file."""
    files = [
        SourceFile.from_text(
            FAKE_INSTRUMENTS, rel=Project.INSTRUMENTS_REL
        ),
        SourceFile.from_text(dedent(text), rel=rel),
    ]
    project = Project(REPO, files)
    diags = analyze(
        project,
        [RULES[r] for r in rule_ids],
        audit_suppressions=audit,
    )
    return [d for d in diags if d.path == rel]


def rule_ids_of(diags):
    return [d.rule_id for d in diags]


# --------------------------------------------------------------- framework
class TestFramework:
    def test_syntax_error_is_a_diagnostic(self):
        diags = run_rules('def broken(:\n', ['unused-import'])
        assert rule_ids_of(diags) == [SYNTAX_ERROR]

    def test_suppression_same_line(self):
        diags = run_rules(
            'import os  # distlint: disable=unused-import -- doc example\n',
            ['unused-import'],
        )
        assert diags == []

    def test_suppression_standalone_comment_covers_next_line(self):
        diags = run_rules(
            '# distlint: disable=unused-import -- doc example\n'
            'import os\n',
            ['unused-import'],
        )
        assert diags == []

    def test_suppression_inside_string_literal_is_inert(self):
        diags = run_rules(
            'X = "import os  # distlint: disable=unused-import -- no"\n'
            'import os\n',
            ['unused-import'],
        )
        assert rule_ids_of(diags) == ['unused-import']

    def test_unjustified_suppression_flagged(self):
        diags = run_rules(
            'import os  # distlint: disable=unused-import\n',
            ['unused-import'],
            audit=True,
        )
        # The finding is suppressed, but the naked directive is flagged.
        assert rule_ids_of(diags) == [SUPPRESSION_UNJUSTIFIED]

    def test_unused_suppression_flagged(self):
        diags = run_rules(
            'import os\n'
            'x = os.sep  # distlint: disable=unused-import -- stale\n',
            ['unused-import'],
            audit=True,
        )
        assert rule_ids_of(diags) == [SUPPRESSION_UNUSED]

    def test_unknown_rule_suppression_flagged(self):
        diags = run_rules(
            'x = 1  # distlint: disable=no-such-rule -- typo\n',
            ['unused-import'],
            audit=True,
        )
        assert SUPPRESSION_UNKNOWN_RULE in rule_ids_of(diags)

    def test_meta_rule_suppression_flagged(self):
        """disable=<meta-rule> can never work (meta rules are
        unsuppressible) — the dead directive must be flagged, not
        accumulate silently outside both the match and unused audits."""
        diags = run_rules(
            'x = 1  # distlint: disable=suppression-unused -- futile\n',
            ['unused-import'],
            audit=True,
        )
        assert rule_ids_of(diags) == [SUPPRESSION_UNKNOWN_RULE]
        assert 'not suppressible' in diags[0].message

    def test_multi_rule_suppression(self):
        diags = run_rules(
            'import os  # distlint: disable=unused-import,raw-print -- both\n',
            ['unused-import'],
        )
        assert diags == []


# ------------------------------------------------------------ hygiene rules
class TestUnusedImport:
    def test_violation(self):
        diags = run_rules('import os\n', ['unused-import'])
        assert rule_ids_of(diags) == ['unused-import']
        assert diags[0].line == 1

    def test_clean(self):
        assert run_rules('import os\nX = os.sep\n', ['unused-import']) == []

    def test_noqa_exempts(self):
        text = 'import os  # noqa: F401\n'
        assert run_rules(text, ['unused-import']) == []

    def test_init_py_out_of_scope(self):
        diags = run_rules(
            'import os\n', ['unused-import'],
            rel='distllm_tpu/sub/__init__.py',
        )
        assert diags == []


class TestRawPrint:
    def test_violation(self):
        diags = run_rules("print('hello')\n", ['raw-print'])
        assert rule_ids_of(diags) == ['raw-print']

    def test_clean(self):
        assert run_rules("log_event('hello')\n", ['raw-print']) == []

    def test_observability_exempt(self):
        diags = run_rules(
            "print('x')\n", ['raw-print'],
            rel='distllm_tpu/observability/metrics.py',
        )
        assert diags == []

    def test_suppressed(self):
        diags = run_rules(
            "print('x')  # distlint: disable=raw-print -- CLI output\n",
            ['raw-print'],
        )
        assert diags == []


class TestDirectFree:
    def test_violation(self):
        diags = run_rules('def f(a):\n    a.free(1)\n', ['direct-free'])
        assert rule_ids_of(diags) == ['direct-free']

    def test_allocator_module_exempt(self):
        diags = run_rules(
            'def f(a):\n    a.free(1)\n', ['direct-free'],
            rel='distllm_tpu/generate/engine/kv_cache.py',
        )
        assert diags == []


ENGINE_REL = 'distllm_tpu/generate/engine/_fixture.py'


class TestSwallowedException:
    """swallowed-exception: in engine/server/tier/resilience paths, an
    ``except`` must re-raise or emit telemetry (ISSUE 15)."""

    def test_silent_pass_flagged(self):
        diags = run_rules(
            'def f(x):\n'
            '    try:\n'
            '        x.go()\n'
            '    except ValueError:\n'
            '        pass\n',
            ['swallowed-exception'],
            rel=ENGINE_REL,
        )
        assert rule_ids_of(diags) == ['swallowed-exception']
        assert diags[0].line == 4

    def test_silent_return_flagged(self):
        diags = run_rules(
            'def f(x):\n'
            '    try:\n'
            '        return x.go()\n'
            '    except Exception:\n'
            '        return None\n',
            ['swallowed-exception'],
            rel=ENGINE_REL,
        )
        assert rule_ids_of(diags) == ['swallowed-exception']

    def test_reraise_clean(self):
        diags = run_rules(
            'def f(x):\n'
            '    try:\n'
            '        x.go()\n'
            '    except ValueError:\n'
            '        raise RuntimeError("context")\n',
            ['swallowed-exception'],
            rel=ENGINE_REL,
        )
        assert diags == []

    def test_metric_emission_clean(self):
        diags = run_rules(
            'def f(x, m):\n'
            '    try:\n'
            '        x.go()\n'
            '    except ValueError:\n'
            "        m.labels(tier='disk').inc()\n",
            ['swallowed-exception'],
            rel=ENGINE_REL,
        )
        assert diags == []

    def test_log_event_clean(self):
        diags = run_rules(
            'def f(x):\n'
            '    try:\n'
            '        x.go()\n'
            '    except ValueError as exc:\n'
            '        log_event(f"failed: {exc}")\n',
            ['swallowed-exception'],
            rel=ENGINE_REL,
        )
        assert diags == []

    def test_flight_record_clean(self):
        diags = run_rules(
            'def f(self, x):\n'
            '    try:\n'
            '        x.go()\n'
            '    except ValueError as exc:\n'
            "        self.flight.record('event', error=repr(exc))\n",
            ['swallowed-exception'],
            rel=ENGINE_REL,
        )
        assert diags == []

    def test_telemetry_note_clean(self):
        diags = run_rules(
            'def f(self, x):\n'
            '    try:\n'
            '        x.go()\n'
            '    except ValueError as exc:\n'
            "        self.telemetry['fallback'] = repr(exc)\n",
            ['swallowed-exception'],
            rel=ENGINE_REL,
        )
        assert diags == []

    def test_out_of_scope_path_exempt(self):
        # The rule is scoped to serving-critical paths; ordinary library
        # modules keep their idioms.
        diags = run_rules(
            'def f(x):\n'
            '    try:\n'
            '        x.go()\n'
            '    except ValueError:\n'
            '        pass\n',
            ['swallowed-exception'],
        )
        assert diags == []

    def test_suppressed(self):
        diags = run_rules(
            'def f(x):\n'
            '    try:\n'
            '        x.go()\n'
            '    # distlint: disable=swallowed-exception -- membership probe\n'
            '    except ValueError:\n'
            '        pass\n',
            ['swallowed-exception'],
            rel=ENGINE_REL,
        )
        assert diags == []

    def test_unused_suppression_flagged(self):
        diags = run_rules(
            'def f(x):\n'
            '    try:\n'
            '        x.go()\n'
            '    # distlint: disable=swallowed-exception -- stale\n'
            '    except ValueError:\n'
            '        raise\n',
            ['swallowed-exception'],
            rel=ENGINE_REL,
            audit=True,
        )
        assert rule_ids_of(diags) == [SUPPRESSION_UNUSED]


# ------------------------------------------------------------ catalog rules
class TestMetricNameCatalog:
    def test_adhoc_registration_flagged(self):
        diags = run_rules(
            "def f(reg):\n    return reg.counter('distllm_rogue_total')\n",
            ['metric-name-catalog'],
        )
        assert rule_ids_of(diags) == ['metric-name-catalog']

    def test_docstring_reference_flagged(self):
        diags = run_rules(
            '"""Reports distllm_phantom_total per window."""\n',
            ['metric-name-catalog'],
        )
        assert rule_ids_of(diags) == ['metric-name-catalog']

    def test_registered_name_clean(self):
        diags = run_rules(
            '"""Reports distllm_good_total per window."""\n'
            "def f(reg):\n    return reg.counter('distllm_good_total')\n",
            ['metric-name-catalog'],
        )
        assert diags == []

    def test_exposition_suffix_clean(self):
        diags = run_rules(
            '"""See distllm_good_total_bucket in the scrape."""\n',
            ['metric-name-catalog'],
        )
        assert diags == []

    def test_named_constant_registration_flagged(self):
        """A metric registered through a module string constant is a
        registration context too — the legacy everywhere-scan caught the
        literal at its definition site, and the scoped rule must not let
        `counter(_NAME)` reopen silent series drift."""
        diags = run_rules(
            "_NAME = 'distllm_rogue_total'\n"
            'def f(reg):\n    return reg.counter(_NAME)\n',
            ['metric-name-catalog'],
        )
        assert rule_ids_of(diags) == ['metric-name-catalog']

    def test_annotated_constant_registration_flagged(self):
        """`_NAME: Final = '...'` binds the same way — AnnAssign must
        not slip past the named-constant resolution."""
        diags = run_rules(
            'from typing import Final\n'
            "_NAME: Final = 'distllm_rogue_total'\n"
            'def f(reg):\n    return reg.counter(_NAME)\n',
            ['metric-name-catalog'],
        )
        assert rule_ids_of(diags) == ['metric-name-catalog']

    def test_named_constant_registration_clean_when_cataloged(self):
        diags = run_rules(
            "_NAME = 'distllm_good_total'\n"
            'def f(reg):\n    return reg.counter(_NAME)\n',
            ['metric-name-catalog'],
        )
        assert diags == []

    def test_instruments_docstring_typo_flagged(self):
        """instruments.py registration CALLS are the catalog (exempt),
        but its docstrings still document series and must not drift —
        the legacy everywhere-scan covered them."""
        files = [
            SourceFile.from_text(
                '"""Catalog. Reports distllm_phantom_total."""\n'
                + FAKE_INSTRUMENTS,
                rel=Project.INSTRUMENTS_REL,
            ),
        ]
        diags = analyze(
            Project(REPO, files), [RULES['metric-name-catalog']],
            audit_suppressions=False,
        )
        assert rule_ids_of(diags) == ['metric-name-catalog']
        assert 'distllm_phantom_total' in diags[0].message

    def test_contextvar_identifier_not_flagged(self):
        """The PR 7 workaround class: an identifier-shaped string OUTSIDE
        registration/exposition contexts is not a metric reference."""
        diags = run_rules(
            'import contextvars\n'
            "V = contextvars.ContextVar('distllm_request_id', default=None)\n",
            ['metric-name-catalog'],
        )
        assert diags == []


class TestFlightKindCatalog:
    def test_violation(self):
        diags = run_rules(
            "def f(rec):\n    rec.record('rogue', x=1)\n",
            ['flight-kind-catalog'],
        )
        assert rule_ids_of(diags) == ['flight-kind-catalog']

    def test_ifexp_branches_checked(self):
        diags = run_rules(
            "def f(rec, m):\n"
            "    rec.record('decode' if m else 'rogue')\n",
            ['flight-kind-catalog'],
        )
        assert rule_ids_of(diags) == ['flight-kind-catalog']

    def test_clean(self):
        diags = run_rules(
            "def f(rec):\n    rec.record('decode', x=1)\n",
            ['flight-kind-catalog'],
        )
        assert diags == []


class TestTraceCategoryCatalog:
    def test_kwarg_violation(self):
        diags = run_rules(
            "def f(emit):\n    emit(cat='rogue')\n",
            ['trace-category-catalog'],
        )
        assert rule_ids_of(diags) == ['trace-category-catalog']

    def test_dict_key_violation(self):
        diags = run_rules(
            "EVENT = {'cat': 'rogue', 'ph': 'X'}\n",
            ['trace-category-catalog'],
        )
        assert rule_ids_of(diags) == ['trace-category-catalog']

    def test_clean(self):
        diags = run_rules(
            "EVENT = {'cat': 'engine'}\n"
            "def f(emit):\n    emit(cat='engine')\n",
            ['trace-category-catalog'],
        )
        assert diags == []


class TestCompilePhaseCatalog:
    def test_violation(self):
        diags = run_rules(
            "def f(w):\n    with w.phase('rogue', 'shape'):\n        pass\n",
            ['compile-phase-catalog'],
        )
        assert rule_ids_of(diags) == ['compile-phase-catalog']

    def test_clean(self):
        diags = run_rules(
            "def f(w):\n    with w.phase('warmup', 'shape'):\n        pass\n",
            ['compile-phase-catalog'],
        )
        assert diags == []


# ---------------------------------------------------------------- TPU rules
HOT_PREAMBLE = 'import numpy as np\nimport jax.numpy as jnp\n'


class TestHostSyncInHotPath:
    def test_stale_hot_paths_entry_flagged(self):
        """A renamed engine/model function must not silently shrink the
        hot-path surface: every HOT_PATHS qualname is audited against
        the source it names."""
        engine_rel = 'distllm_tpu/generate/engine/engine.py'
        files = [
            SourceFile.from_text(
                'class LLMEngine:\n    def step(self):\n        pass\n',
                rel=engine_rel,
            ),
        ]
        diags = analyze(Project(REPO, files), [RULES['host-sync-in-hot-path']])
        stale = [d for d in diags if 'HOT_PATHS entry' in d.message]
        # Every listed engine qualname except LLMEngine.step is missing
        # from the stub; mistral.py is not in this project -> skipped.
        from distllm_tpu.analysis.rules_tpu import HostSyncInHotPathRule
        expected = len(HostSyncInHotPathRule.HOT_PATHS[engine_rel]) - 1
        assert len(stale) == expected
        assert all(d.path == engine_rel for d in stale)

    def test_hot_paths_entries_resolve_in_repo(self):
        """The shipped HOT_PATHS table matches today's source (the
        self-run also proves this, but pin it directly)."""
        from distllm_tpu.analysis.core import load_project
        from distllm_tpu.analysis.rules_tpu import HostSyncInHotPathRule
        rule = HostSyncInHotPathRule.__new__(HostSyncInHotPathRule)
        paths = [REPO / rel for rel in HostSyncInHotPathRule.HOT_PATHS]
        project = load_project(REPO, paths)
        assert list(rule.check_project(project)) == []

    def test_violations(self):
        diags = run_rules(
            HOT_PREAMBLE
            + 'def loop(self):  # distlint: hot-path\n'
            '    toks = self._decode_window(1)\n'
            '    a = np.asarray(toks)\n'
            '    b = toks.item()\n'
            '    c = toks.tolist()\n'
            '    d = int(toks)\n'
            '    toks.block_until_ready()\n'
            '    return a, b, c, d\n',
            ['host-sync-in-hot-path'],
        )
        assert rule_ids_of(diags) == ['host-sync-in-hot-path'] * 5

    def test_clean_host_only_math(self):
        diags = run_rules(
            HOT_PREAMBLE
            + 'def loop(self, lengths):  # distlint: hot-path\n'
            '    total = int(lengths.sum())\n'
            '    ids = np.zeros((4,), np.int32)\n'
            '    return total, ids\n',
            ['host-sync-in-hot-path'],
        )
        assert diags == []

    def test_host_copy_ends_tracking(self):
        """int() of an np.asarray result is free — the sync was already
        charged to the asarray (which needs its own suppression)."""
        diags = run_rules(
            HOT_PREAMBLE
            + 'def loop(self):  # distlint: hot-path\n'
            '    toks = self._decode_window(1)\n'
            '    # distlint: disable=host-sync-in-hot-path -- designed fetch point\n'
            '    host = np.asarray(toks)\n'
            '    return int(host[0])\n',
            ['host-sync-in-hot-path'],
        )
        assert diags == []

    def test_method_sync_on_host_copy_free(self):
        """.tolist()/.item() of the fetched numpy copy is free — the
        sync was already charged (and suppressed) at the asarray; the
        same methods on a device value or an unknown receiver stay
        flagged."""
        diags = run_rules(
            HOT_PREAMBLE
            + 'def loop(self):  # distlint: hot-path\n'
            '    toks = self._decode_window(1)\n'
            '    # distlint: disable=host-sync-in-hot-path -- designed fetch point\n'
            '    host = np.asarray(toks)\n'
            '    ids = host.tolist()\n'
            '    first = host[0].item()\n'
            '    bad = toks.tolist()\n'
            '    unknown = self.window.tolist()\n'
            '    return ids, first, bad, unknown\n',
            ['host-sync-in-hot-path'],
        )
        # Only the device receiver (toks) and the untracked receiver
        # (self.window) are flagged.
        assert [d.line for d in diags] == [9, 10]

    def test_not_hot_function_ignored(self):
        diags = run_rules(
            HOT_PREAMBLE
            + 'def warmup(self):\n'
            '    toks = self._decode_window(1)\n'
            '    return np.asarray(toks)\n',
            ['host-sync-in-hot-path'],
        )
        assert diags == []

    def test_builtin_hot_paths_cover_engine_window_loop(self):
        from distllm_tpu.analysis.rules_tpu import HostSyncInHotPathRule

        rule = HostSyncInHotPathRule()
        engine_rel = 'distllm_tpu/generate/engine/engine.py'
        assert 'LLMEngine._dispatch_window' in rule.HOT_PATHS[engine_rel]
        src = SourceFile.from_path(REPO / engine_rel, REPO)
        hot = {q for q, _ in rule._hot_functions(src)}
        assert 'LLMEngine._dispatch_window' in hot
        # process_one moved with the loop body when _run_to_completion
        # grew its crash-domain recovery wrapper (ISSUE 15).
        assert 'LLMEngine._serve_pipelined.<locals>.process_one' in hot


class TestTracedPythonBranch:
    def test_if_on_traced_value(self):
        diags = run_rules(
            'import jax\nimport jax.numpy as jnp\n'
            '@jax.jit\n'
            'def f(x):\n'
            '    s = jnp.sum(x)\n'
            '    if s > 0:\n'
            '        return s\n'
            '    return -s\n',
            ['traced-python-branch'],
        )
        assert rule_ids_of(diags) == ['traced-python-branch']

    def test_while_and_assert(self):
        diags = run_rules(
            'import jax\nimport jax.numpy as jnp\n'
            '@jax.jit\n'
            'def f(x):\n'
            '    s = jnp.sum(x)\n'
            '    assert s > 0\n'
            '    while s < 10:\n'
            '        s = s + 1\n'
            '    return s\n',
            ['traced-python-branch'],
        )
        assert rule_ids_of(diags) == ['traced-python-branch'] * 2

    def test_shape_branch_is_static_and_clean(self):
        diags = run_rules(
            'import jax\nimport jax.numpy as jnp\n'
            '@jax.jit\n'
            'def f(x):\n'
            '    y = jnp.pad(x, 2)\n'
            '    m, k = y.shape\n'
            '    if m > k:\n'
            '        return y\n'
            '    return y.T\n',
            ['traced-python-branch'],
        )
        assert diags == []

    def test_untraced_function_clean(self):
        diags = run_rules(
            'import jax.numpy as jnp\n'
            'def host_helper(x):\n'
            '    s = jnp.sum(x)\n'
            '    if s > 0:\n'
            '        return s\n'
            '    return -s\n',
            ['traced-python-branch'],
        )
        assert diags == []

    def test_isinstance_dispatch_is_static_and_clean(self):
        # The QuantizedKV-vs-bare-array pytree dispatch idiom
        # (ops/paged_attention.py write paths): isinstance inspects the
        # container's Python type at trace time — never a traced value —
        # even when the SAME name is later rebound from a device
        # expression (the flow-insensitive fixpoint must not leak that
        # back into the isinstance test).
        diags = run_rules(
            'import jax\nimport jax.numpy as jnp\n'
            '@jax.jit\n'
            'def f(cache, new):\n'
            '    if isinstance(cache, tuple):\n'
            '        return cache\n'
            '    cache = cache + jnp.sum(new)\n'
            '    return cache\n',
            ['traced-python-branch'],
        )
        assert diags == []

    def test_isinstance_bound_flag_is_static_and_clean(self):
        # `quantized = isinstance(...)` is a static bool, not a
        # device-derived value — branching on it later stays clean
        # (engine._write_prefill_all_layers).
        diags = run_rules(
            'import jax\nimport jax.numpy as jnp\n'
            '@jax.jit\n'
            'def f(cache, new):\n'
            '    cache = cache + jnp.sum(new)\n'
            '    quantized = isinstance(cache, tuple)\n'
            '    if quantized:\n'
            '        return cache\n'
            '    return -cache\n',
            ['traced-python-branch'],
        )
        assert diags == []

    def test_closure_reaches_scan_body(self):
        diags = run_rules(
            'import jax\nimport jax.numpy as jnp\n'
            'from jax import lax\n'
            'def layer(c, x):\n'
            '    s = jnp.sum(x)\n'
            '    if s > 0:\n'
            '        return c, x\n'
            '    return c, -x\n'
            '@jax.jit\n'
            'def f(xs):\n'
            '    return lax.scan(layer, 0, xs)\n',
            ['traced-python-branch'],
        )
        assert rule_ids_of(diags) == ['traced-python-branch']


class TestNondeterminismInDispatch:
    def test_time_in_traced(self):
        diags = run_rules(
            'import jax\nimport time\n'
            '@jax.jit\n'
            'def f(x):\n'
            '    return x + time.time()\n',
            ['nondeterminism-in-dispatch'],
        )
        assert rule_ids_of(diags) == ['nondeterminism-in-dispatch']

    def test_np_random_in_traced(self):
        diags = run_rules(
            'import jax\nimport numpy as np\n'
            '@jax.jit\n'
            'def f(x):\n'
            '    return x + np.random.rand()\n',
            ['nondeterminism-in-dispatch'],
        )
        assert rule_ids_of(diags) == ['nondeterminism-in-dispatch']

    def test_jax_random_clean(self):
        diags = run_rules(
            'import jax\n'
            '@jax.jit\n'
            'def f(x, key):\n'
            '    return x + jax.random.normal(key, x.shape)\n',
            ['nondeterminism-in-dispatch'],
        )
        assert diags == []

    def test_host_function_clean(self):
        diags = run_rules(
            'import time\n'
            'def budget():\n'
            '    return time.monotonic()\n',
            ['nondeterminism-in-dispatch'],
        )
        assert diags == []


LOCK_PREAMBLE = (
    'import threading\n'
    'class C:\n'
    '    def __init__(self):\n'
    '        self._lock = threading.Lock()\n'
    '        self._items = []  # guarded by self._lock\n'
)


class TestLockDiscipline:
    def test_unlocked_read_flagged(self):
        diags = run_rules(
            LOCK_PREAMBLE
            + '    def peek(self):\n'
            '        return len(self._items)\n',
            ['lock-discipline'],
        )
        assert rule_ids_of(diags) == ['lock-discipline']

    def test_locked_access_clean(self):
        diags = run_rules(
            LOCK_PREAMBLE
            + '    def add(self, x):\n'
            '        with self._lock:\n'
            '            self._items.append(x)\n',
            ['lock-discipline'],
        )
        assert diags == []

    def test_holds_lock_def_annotation(self):
        diags = run_rules(
            LOCK_PREAMBLE
            + '    def _drain_locked(self):  # guarded by self._lock\n'
            '        out = list(self._items)\n'
            '        self._items.clear()\n'
            '        return out\n',
            ['lock-discipline'],
        )
        assert diags == []

    def test_unlocked_write_flagged(self):
        diags = run_rules(
            LOCK_PREAMBLE
            + '    def reset(self):\n'
            '        self._items = []\n',
            ['lock-discipline'],
        )
        assert rule_ids_of(diags) == ['lock-discipline']

    def test_annotation_inside_hot_method_does_not_exempt_it(self):
        """An annotated assignment in a non-constructor method exempts
        NOTHING — not even its own line. Letting the annotation silence
        the finding would be an unaudited suppression channel (annotate
        the racy write and the detector goes quiet exactly there); the
        only sanctioned escape is a justified `# distlint: disable`."""
        diags = run_rules(
            'import threading\n'
            'class C:\n'
            '    def __init__(self):\n'
            '        self._lock = threading.Lock()\n'
            '    def reset(self):\n'
            '        self._items = []  # guarded by self._lock\n'
            '        return len(self._items)\n',
            ['lock-discipline'],
        )
        # Both the annotated unlocked write (line 6) and the unlocked
        # read (line 7) are races.
        assert rule_ids_of(diags) == ['lock-discipline', 'lock-discipline']
        assert [d.line for d in diags] == [6, 7]

    def test_closure_under_lock_not_blessed(self):
        """A callback DEFINED inside `with self._lock:` executes later,
        without the lock — the watchdog-timer race class the rule was
        built for. Line containment must not bless its body."""
        diags = run_rules(
            'import threading\n'
            'class C:\n'
            '    def __init__(self):\n'
            '        self._lock = threading.Lock()\n'
            '        self._active = {}  # guarded by self._lock\n'
            '    def arm(self):\n'
            '        with self._lock:\n'
            '            cb = lambda: self._active.pop(1)\n'
            '            self._timer = threading.Timer(1.0, cb)\n'
            '    def sync_use(self):\n'
            '        with self._lock:\n'
            '            return len(self._active)\n',
            ['lock-discipline'],
        )
        assert rule_ids_of(diags) == ['lock-discipline']
        assert diags[0].line == 8

    def test_annotated_write_under_lock_is_clean(self):
        diags = run_rules(
            'import threading\n'
            'class C:\n'
            '    def __init__(self):\n'
            '        self._lock = threading.Lock()\n'
            '    def reset(self):\n'
            '        with self._lock:\n'
            '            self._items = []  # guarded by self._lock\n',
            ['lock-discipline'],
        )
        assert diags == []

    def test_unannotated_class_ignored(self):
        diags = run_rules(
            'import threading\n'
            'class C:\n'
            '    def __init__(self):\n'
            '        self._lock = threading.Lock()\n'
            '        self._items = []\n'
            '    def peek(self):\n'
            '        return len(self._items)\n',
            ['lock-discipline'],
        )
        assert diags == []


# ----------------------------------------------------- traced-index details
class TestTracedIndex:
    def test_partial_wrapped_pallas_kernel_detected(self):
        src = SourceFile.from_text(
            dedent(
                '''
                import functools
                import jax
                from jax.experimental import pallas as pl
                def _kernel(x_ref, o_ref, *, steps):
                    o_ref[...] = x_ref[...]
                def op(x):
                    return pl.pallas_call(
                        functools.partial(_kernel, steps=2),
                        out_shape=None,
                    )(x)
                '''
            ),
            rel=FIXTURE_REL,
        )
        index = TracedIndex(src)
        assert '_kernel' in index.traced

    def test_partial_bound_on_own_line_detected(self):
        # The repo's real kernels bind the partial to a name first
        # (ops/paged_attention.py) — the wrap-site scan must resolve
        # that alias or the hottest traced code goes unlinted.
        src = SourceFile.from_text(
            dedent(
                '''
                import functools
                from jax.experimental import pallas as pl
                def _kernel(x_ref, o_ref, *, steps):
                    o_ref[...] = x_ref[...]
                def op(x):
                    kernel = functools.partial(_kernel, steps=2)
                    return pl.pallas_call(kernel, out_shape=None)(x)
                '''
            ),
            rel=FIXTURE_REL,
        )
        index = TracedIndex(src)
        assert '_kernel' in index.traced

    def test_control_flow_function_operands_seeded(self):
        """while_loop/fori_loop bodies and cond/switch branches are the
        traced code — they sit past args[0], so the wrap-site scan must
        look at every function-valued operand."""
        src = SourceFile.from_text(
            dedent(
                '''
                from jax import lax
                def _pred(s):
                    return s[0]
                def _body(s):
                    return s
                def _tf(x):
                    return x
                def _ff(x):
                    return x
                def _b0(x):
                    return x
                def _b1(x):
                    return x
                def op(x):
                    y = lax.while_loop(_pred, _body, x)
                    z = lax.cond(True, _tf, _ff, y)
                    w = lax.fori_loop(0, 3, _body, z)
                    return lax.switch(0, [_b0, _b1], w)
                '''
            ),
            rel=FIXTURE_REL,
        )
        index = TracedIndex(src)
        for expected in ('_pred', '_body', '_tf', '_ff', '_b0', '_b1'):
            assert expected in index.traced, f'{expected} not traced'

    def test_marker_seeds_tracing(self):
        src = SourceFile.from_text(
            'def dispatch(x):  # distlint: traced\n'
            '    return helper(x)\n'
            'def helper(x):\n'
            '    return x\n',
            rel=FIXTURE_REL,
        )
        index = TracedIndex(src)
        assert {'dispatch', 'helper'} <= index.traced

    def test_model_dispatch_surface_is_traced(self):
        """The cross-module-jitted model entry points carry markers, and
        the closure reaches their layer bodies."""
        src = SourceFile.from_path(
            REPO / 'distllm_tpu/models/mistral.py', REPO
        )
        index = TracedIndex(src)
        for expected in ('mixed_window', 'spec_window', 'decode_step',
                         'prefill_paged', '_forward'):
            assert any(
                q == expected or q.endswith('.' + expected)
                for q in index.traced
            ), f'{expected} not traced'

    def test_kv_write_and_kernel_surface_is_traced(self):
        """The paged-attention Pallas kernel (partial bound on its own
        line) and the cross-module KV-write helpers are all visible to
        the traced rules."""
        src = SourceFile.from_path(
            REPO / 'distllm_tpu/ops/paged_attention.py', REPO
        )
        index = TracedIndex(src)
        for expected in ('_ragged_paged_attn_kernel', 'write_token_kv',
                         'write_chunk_kv', 'write_prefill_kv'):
            assert expected in index.traced, f'{expected} not traced'
        mix = SourceFile.from_path(
            REPO / 'distllm_tpu/models/mixtral.py', REPO
        )
        assert 'moe_mlp' in TracedIndex(mix).traced


# ------------------------------------------------------------- end to end
class TestEndToEnd:
    def test_repo_is_clean(self):
        report = build_report(REPO)
        assert report['summary']['total'] == 0, json.dumps(
            report['diagnostics'], indent=2
        )

    def test_json_schema_stable(self):
        report = build_report(REPO)
        assert report['version'] == 1
        assert sorted(report) == [
            'diagnostics', 'files_analyzed', 'root', 'rules', 'summary',
            'version',
        ]
        assert report['files_analyzed'] > 100
        assert sorted(report['summary']) == ['by_rule', 'total']
        rule_entry = report['rules'][0]
        assert sorted(rule_entry) == ['description', 'id', 'severity']

    def test_json_diagnostic_schema(self, tmp_path):
        # A root with its own tiny catalog and one dirty file: exercises
        # the CLI subprocess, the nonzero exit, and the diagnostic keys.
        pkg = tmp_path / 'distllm_tpu'
        (pkg / 'observability').mkdir(parents=True)
        (pkg / 'observability' / 'instruments.py').write_text(
            FAKE_INSTRUMENTS
        )
        (pkg / 'bad.py').write_text('import os\nprint("hi")\n')
        proc = subprocess.run(
            [
                sys.executable, str(REPO / 'scripts' / 'distlint.py'),
                '--root', str(tmp_path), '--json',
            ],
            capture_output=True, text=True,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        assert report['summary']['total'] == 2
        assert sorted(report['summary']['by_rule']) == [
            'raw-print', 'unused-import',
        ]
        for diag in report['diagnostics']:
            assert sorted(diag) == [
                'line', 'message', 'path', 'rule_id', 'severity',
            ]
            assert diag['path'] == 'distllm_tpu/bad.py'

    def test_cli_exit_zero_on_clean_repo(self):
        proc = subprocess.run(
            [sys.executable, str(REPO / 'scripts' / 'distlint.py')],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert 'clean' in proc.stdout

    def test_cli_list_rules(self):
        proc = subprocess.run(
            [
                sys.executable, str(REPO / 'scripts' / 'distlint.py'),
                '--list-rules',
            ],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0
        for rule_id in RULES:
            assert rule_id in proc.stdout

    def test_cli_rule_subset(self):
        proc = subprocess.run(
            [
                sys.executable, str(REPO / 'scripts' / 'distlint.py'),
                '--rules', 'raw-print',
            ],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_cli_unknown_rule_errors(self):
        proc = subprocess.run(
            [
                sys.executable, str(REPO / 'scripts' / 'distlint.py'),
                '--rules', 'no-such-rule',
            ],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 2

    def test_single_parse_per_file(self, monkeypatch):
        """The driver parses each file exactly once regardless of how
        many rules run (the legacy gate re-parsed per rule, ~8×)."""
        import ast as ast_module

        calls: list[str] = []
        real_parse = ast_module.parse

        def counting_parse(source, filename='<unknown>', *a, **k):
            calls.append(str(filename))
            return real_parse(source, filename, *a, **k)

        monkeypatch.setattr(ast_module, 'parse', counting_parse)
        run_rules('X = 1\n', sorted(RULES))
        fixture_parses = [c for c in calls if c == FIXTURE_REL]
        assert len(fixture_parses) == 1
