"""The shipped examples/ surface must stay runnable.

Every YAML parses into its entry point's Config class; the fake/local ones
execute end-to-end; the scheduler-submitted pod configs render correct
PBS/sbatch job scripts (reference analogue: parsl.py:106-252).
"""

from __future__ import annotations

import json
from pathlib import Path

import pytest

EXAMPLES = Path(__file__).parent.parent / 'examples'


def test_examples_tree_exists():
    assert (EXAMPLES / 'README.md').exists()


@pytest.mark.parametrize(
    'rel, config_cls',
    [
        ('embed/jsonl_chunk.fake.local.yaml', 'embed'),
        ('embed/semantic_chunk.sfr-mistral.pod-pbs.nodes256.yaml', 'embed'),
        ('embed/esm2.fasta.workstation.yaml', 'embed'),
        ('embed/modernbert.jsonl_chunk.workstation.yaml', 'embed'),
        ('generate/question_chunk.fake.local.yaml', 'generate'),
        ('generate/mistral7b.tpu.pod-slurm.nodes16.yaml', 'generate'),
        ('generate/mixtral8x7b.tpu.tp8.yaml', 'generate'),
        ('tokenize/jsonl.local.yaml', 'tokenize'),
        ('mcqa/mcqa.local.yaml', 'mcqa'),
        ('mcqa/mcqa.boot-local-engine.yaml', 'mcqa'),
        ('chat/chat.fake.yaml', 'chat'),
        ('chat/chat_server.rag.yaml', 'chat'),
        ('evaluate/eval.fake.local.yaml', 'evaluate'),
    ],
)
def test_example_parses(rel, config_cls):
    path = EXAMPLES / rel
    if config_cls == 'embed':
        from distllm_tpu.distributed_embedding import Config
    elif config_cls == 'generate':
        from distllm_tpu.distributed_generation import Config
    elif config_cls == 'tokenize':
        from distllm_tpu.distributed_tokenization import Config
    elif config_cls == 'mcqa':
        from distllm_tpu.mcqa import MCQAConfig as Config
    elif config_cls == 'chat':
        from distllm_tpu.chat import ChatAppConfig as Config
    else:
        from distllm_tpu.rag.evaluate import EvalSuiteConfig as Config
    cfg = Config.from_yaml(path)
    assert cfg is not None
    # The outer Config holds generator_config as a raw dict (validated on
    # the worker) — construct the registered generator config here so a
    # shipped example cannot pass CI while failing at worker startup.
    gen_dict = getattr(cfg, 'generator_config', None)
    if isinstance(gen_dict, dict) and gen_dict.get('name') in ('tpu', 'vllm'):
        from distllm_tpu.generate.generators.tpu_backend import (
            TpuGeneratorConfig,
        )

        inner = dict(gen_dict)
        inner.pop('name')
        TpuGeneratorConfig(**inner)


def test_model_servers_registry_parses():
    from distllm_tpu.mcqa.config import load_model_servers

    registry = load_model_servers(EXAMPLES / 'mcqa' / 'model_servers.yaml')
    assert 'local-tpu' in registry and 'grader' in registry
    assert registry['grader'].openai_api_base.startswith('http')


def test_embed_fake_example_runs(tmp_path, monkeypatch):
    from distllm_tpu.distributed_embedding import Config, run_embedding

    (tmp_path / 'inputs').mkdir()
    rows = [json.dumps({'text': f'doc {i} about proteins'}) for i in range(6)]
    (tmp_path / 'inputs' / 'a.jsonl').write_text('\n'.join(rows))
    monkeypatch.chdir(tmp_path)
    cfg = Config.from_yaml(EXAMPLES / 'embed' / 'jsonl_chunk.fake.local.yaml')
    assert run_embedding(cfg) == 0
    shards = list((tmp_path / 'outputs' / 'embed_fake' / 'embeddings').iterdir())
    assert shards


def test_generate_fake_example_runs(tmp_path, monkeypatch):
    from distllm_tpu.distributed_generation import Config, run_generation

    (tmp_path / 'inputs').mkdir()
    rows = [json.dumps({'text': f'what is item {i}?', 'path': f'p{i}'}) for i in range(4)]
    (tmp_path / 'inputs' / 'q.jsonl').write_text('\n'.join(rows))
    monkeypatch.chdir(tmp_path)
    cfg = Config.from_yaml(
        EXAMPLES / 'generate' / 'question_chunk.fake.local.yaml'
    )
    assert run_generation(cfg) == 0


def test_chat_fake_example_builds_session(tmp_path, monkeypatch):
    from distllm_tpu.chat import ChatAppConfig, ChatSession

    monkeypatch.chdir(tmp_path)
    cfg = ChatAppConfig.from_yaml(EXAMPLES / 'chat' / 'chat.fake.yaml')
    session = ChatSession(cfg)
    reply = session.ask('hello')
    # FakeGenerator echoes a truncated prompt (system prompt + turns).
    assert reply.startswith('echo:')


def test_pbs_script_renders():
    from distllm_tpu.distributed_embedding import Config

    cfg = Config.from_yaml(
        EXAMPLES / 'embed' / 'semantic_chunk.sfr-mistral.pod-pbs.nodes256.yaml'
    )
    compute = cfg.compute_config
    assert compute.name == 'pbspro'
    script = compute.render_script('tcp://driver:5555', Path('/tmp/run'))
    assert '#PBS -A MyAllocation' in script
    assert '#PBS -q prod' in script
    assert '#PBS -l walltime=01:00:00' in script
    assert '#PBS -l select=256:tpu_accelerator=v5e' in script
    assert '#PBS -l filesystems=home:data' in script
    assert 'source /opt/venv/bin/activate' in script
    assert (
        'mpiexec -n 256 --ppn 1 --envall python -m '
        'distllm_tpu.parallel.worker --coordinator tcp://driver:5555'
        in script
    )
    # Default: independent per-host JAX processes, no global runtime env.
    assert 'DISTLLM_JAX_COORDINATOR' not in script


def test_pbs_script_renders_jax_distributed():
    from distllm_tpu.parallel.launcher import TpuPodPbsConfig

    compute = TpuPodPbsConfig(
        account='acct', queue='q', num_nodes=4, jax_distributed=True,
        jax_coordinator_port=8123, submit=False,
    )
    script = compute.render_script('tcp://driver:5555', Path('/tmp/run'))
    assert (
        'export DISTLLM_JAX_COORDINATOR='
        '"$(head -n1 "$PBS_NODEFILE"):8123"' in script
    )
    assert 'export DISTLLM_JAX_NUM_PROCESSES=4' in script
    assert '--jax-distributed' in script


def test_sbatch_script_renders_jax_distributed():
    from distllm_tpu.parallel.launcher import TpuPodSlurmConfig

    compute = TpuPodSlurmConfig(
        account='acct', queue='q', num_nodes=8, jax_distributed=True,
        submit=False,
    )
    script = compute.render_script('tcp://driver:5555', Path('/tmp/run'))
    assert (
        'export DISTLLM_JAX_COORDINATOR='
        '"$(scontrol show hostnames "$SLURM_JOB_NODELIST" | head -n1):8476"'
        in script
    )
    assert 'export DISTLLM_JAX_NUM_PROCESSES=8' in script
    assert '--jax-distributed' in script


def test_sbatch_script_renders():
    from distllm_tpu.distributed_generation import Config

    cfg = Config.from_yaml(
        EXAMPLES / 'generate' / 'mistral7b.tpu.pod-slurm.nodes16.yaml'
    )
    compute = cfg.compute_config
    assert compute.name == 'slurm'
    script = compute.render_script('tcp://driver:5555', Path('/tmp/run'))
    assert '#SBATCH --account=my_account' in script
    assert '#SBATCH --partition=boost_usr_prod' in script
    assert '#SBATCH --qos=normal' in script
    assert '#SBATCH --nodes=16' in script
    assert (
        'srun --ntasks=16 --ntasks-per-node=1 python -m '
        'distllm_tpu.parallel.worker --coordinator tcp://driver:5555' in script
    )


def test_pbs_submit_dry_run(tmp_path):
    """submit=False writes the script without invoking qsub."""
    from distllm_tpu.parallel.launcher import TpuPodPbsConfig

    compute = TpuPodPbsConfig(
        account='acct', queue='q', num_nodes=2, submit=False,
        coordinator_port=5599,
    )
    executor = compute.get_executor(tmp_path)
    try:
        script = (tmp_path / 'submit.pbs').read_text()
        assert '#PBS -A acct' in script
        assert 'mpiexec -n 2' in script
    finally:
        executor.coordinator.close()


def test_launch_pod_script_exists():
    script = (EXAMPLES / 'pod' / 'launch_pod.sh').read_text()
    assert 'distllm_tpu.parallel.worker' in script
    assert '--coordinator' in script


def test_protein_search_example_runs(tmp_path):
    """FASTA corpus -> fake-encoder embeddings -> exact search, end to end
    through the example app (the reference ships examples/protein_search.py)."""
    import subprocess
    import sys

    from distllm_tpu.distributed_embedding import Config, run_embedding

    (tmp_path / 'inputs').mkdir()
    seqs = ''.join(
        f'>prot{i}\n' + 'ACDEFGHIKLMNPQRSTVWY'[: 5 + i % 12] * 3 + '\n'
        for i in range(8)
    )
    (tmp_path / 'inputs' / 'corpus.fasta').write_text(seqs)
    cfg = Config(
        input_dir=tmp_path / 'inputs',
        output_dir=tmp_path / 'emb',
        glob_patterns=['*.fasta'],
        dataset_config={'name': 'fasta', 'batch_size': 4},
        encoder_config={'name': 'fake', 'embedding_size': 16},
        pooler_config={'name': 'mean'},
        embedder_config={'name': 'full_sequence'},
        writer_config={'name': 'huggingface'},
        compute_config={'name': 'local'},
    )
    assert run_embedding(cfg) == 0
    shard = next((tmp_path / 'emb' / 'embeddings').iterdir())
    queries = tmp_path / 'queries.fasta'
    queries.write_text('>q0\nACDEF\n>q1\nACDEFGHIK\n')
    out = tmp_path / 'hits.jsonl'
    proc = subprocess.run(
        [sys.executable, str(EXAMPLES / 'protein_search.py'),
         '--dataset_dir', str(shard), '--fasta', str(queries),
         '--encoder', 'fake', '--top_k', '3', '--output', str(out)],
        capture_output=True, text=True,
        env={
            **__import__('os').environ,
            'JAX_PLATFORMS': 'cpu',
            # The example has no sys.path bootstrap; make the test work on
            # uninstalled checkouts too.
            'PYTHONPATH': str(EXAMPLES.parent),
        },
    )
    assert proc.returncode == 0, proc.stderr[-500:]
    lines = [json.loads(x) for x in out.read_text().splitlines()]
    assert len(lines) == 2
    # score_threshold=0.0 drops negative-similarity hits (reference
    # semantics), so up to top_k survive.
    assert all(1 <= len(line['hits']) <= 3 for line in lines)
    assert all('tag' in h and 'score' in h for h in lines[0]['hits'])


def test_scaling_ladder_constructs():
    """Every rung of the 2/16/64/256 scaling ladder (reference parity:
    examples/scaling/polaris/*/nodes*.yaml) loads, carries the right node
    count, and renders a submittable job script."""
    from distllm_tpu.distributed_embedding import Config as EmbedConfig
    from distllm_tpu.distributed_generation import Config as GenConfig

    ladder = EXAMPLES / 'pod' / 'scaling'
    rungs = (2, 16, 64, 256)
    for n in rungs:
        embed = EmbedConfig.from_yaml(ladder / 'embed' / f'nodes{n:03d}.yaml')
        assert embed.compute_config.num_nodes == n
        script = embed.compute_config.render_script(
            'tcp://driver:5555', Path('/tmp/run')
        )
        assert f'mpiexec -n {n} ' in script

        gen = GenConfig.from_yaml(ladder / 'generate' / f'nodes{n:03d}.yaml')
        assert gen.compute_config.num_nodes == n
        script = gen.compute_config.render_script(
            'tcp://driver:5555', Path('/tmp/run')
        )
        assert f'srun --ntasks={n} ' in script
    # The ladder is complete: no stray rungs, embed and generate in step.
    for pipeline in ('embed', 'generate'):
        files = sorted(p.name for p in (ladder / pipeline).glob('*.yaml'))
        assert files == [f'nodes{n:03d}.yaml' for n in rungs]
