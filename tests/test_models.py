"""Model numerics parity vs HuggingFace torch (tiny local checkpoints).

No network: tiny random-init HF models are constructed in-process, their
state dicts converted with ``params_from_hf``, and JAX forwards compared to
the torch reference in float32.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distllm_tpu.models import bert as jbert
from distllm_tpu.models import esm2 as jesm
from distllm_tpu.models import mistral as jmistral

torch = pytest.importorskip('torch')


def _to_numpy_state(model):
    return {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}


def _rand_batch(rng, batch, seq, vocab, pad_from=None):
    ids = rng.integers(4, vocab, size=(batch, seq)).astype(np.int32)
    mask = np.ones((batch, seq), np.int32)
    if pad_from is not None:
        for row, start in enumerate(pad_from):
            mask[row, start:] = 0
            ids[row, start:] = 0
    return ids, mask


@pytest.fixture(scope='module')
def np_rng():
    return np.random.default_rng(42)


def test_bert_matches_hf(np_rng):
    from transformers import BertConfig, BertModel

    hf_cfg = BertConfig(
        vocab_size=97,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=64,
        max_position_embeddings=48,
        type_vocab_size=2,
    )
    model = BertModel(hf_cfg).eval()
    cfg = jbert.BertConfig.from_hf_config(hf_cfg.to_dict())
    cfg.dtype = 'float32'
    params = jbert.params_from_hf(_to_numpy_state(model), cfg)

    ids, mask = _rand_batch(np_rng, 3, 16, 97, pad_from=[16, 12, 9])
    with torch.no_grad():
        ref = model(
            input_ids=torch.tensor(ids.astype(np.int64)),
            attention_mask=torch.tensor(mask.astype(np.int64)),
        ).last_hidden_state.numpy()
    ours = np.asarray(jbert.apply(params, cfg, ids, mask))
    # Compare only unpadded positions (padding rows diverge harmlessly).
    valid = mask.astype(bool)
    np.testing.assert_allclose(ours[valid], ref[valid], atol=2e-5, rtol=1e-4)


def test_mistral_matches_hf(np_rng):
    from transformers import MistralConfig, MistralModel

    hf_cfg = MistralConfig(
        vocab_size=101,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        intermediate_size=64,
        max_position_embeddings=64,
        rope_theta=10000.0,
        sliding_window=None,
    )
    model = MistralModel(hf_cfg).eval()
    cfg = jmistral.MistralConfig.from_hf_config(hf_cfg.to_dict())
    cfg.dtype = 'float32'
    params = jmistral.params_from_hf(_to_numpy_state(model), cfg)

    ids, mask = _rand_batch(np_rng, 2, 12, 101)
    with torch.no_grad():
        ref = model(
            input_ids=torch.tensor(ids.astype(np.int64)),
            attention_mask=torch.tensor(mask.astype(np.int64)),
        ).last_hidden_state.numpy()
    ours = np.asarray(jmistral.apply(params, cfg, ids, mask))
    np.testing.assert_allclose(ours, ref, atol=3e-5, rtol=1e-4)


def test_llama3_rope_scaling_matches_hf(np_rng):
    """Llama-3 checkpoints carry rope_scaling (llama3 frequency banding);
    ignoring it mis-positions every token past the original context, so
    the scaled tables are golden-tested against transformers."""
    from transformers import LlamaConfig, LlamaModel

    rope_scaling = {
        'rope_type': 'llama3', 'factor': 8.0, 'low_freq_factor': 1.0,
        'high_freq_factor': 4.0, 'original_max_position_embeddings': 16,
    }
    hf_cfg = LlamaConfig(
        vocab_size=101, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=64,
        max_position_embeddings=128, rope_scaling=rope_scaling,
        attention_bias=False,
    )
    model = LlamaModel(hf_cfg).eval()
    cfg = jmistral.MistralConfig.from_hf_config(hf_cfg.to_dict())
    assert cfg.rope_scaling is not None
    cfg.dtype = 'float32'
    params = jmistral.params_from_hf(_to_numpy_state(model), cfg)

    # Long enough that scaled and unscaled tables genuinely differ.
    ids, mask = _rand_batch(np_rng, 2, 48, 101)
    with torch.no_grad():
        ref = model(
            input_ids=torch.tensor(ids.astype(np.int64)),
            attention_mask=torch.tensor(mask.astype(np.int64)),
        ).last_hidden_state.numpy()
    ours = np.asarray(jmistral.apply(params, cfg, ids, mask))
    np.testing.assert_allclose(ours, ref, atol=5e-5, rtol=1e-4)
    # And the scaling is actually load-bearing at these lengths:
    cfg_unscaled = cfg.model_copy(update={'rope_scaling': None})
    unscaled = np.asarray(jmistral.apply(params, cfg_unscaled, ids, mask))
    assert np.abs(unscaled - ref).max() > 1e-3


def test_rope_scaling_unknown_type_raises():
    from distllm_tpu.models import common as jcommon

    with pytest.raises(NotImplementedError, match='yarn'):
        jcommon.rope_frequencies(
            64, 32, 1e4, {'rope_type': 'yarn', 'factor': 4.0}
        )


def test_qwen2_matches_hf(np_rng):
    """Qwen2 = Mistral architecture + Q/K/V biases; same module serves it
    (auto-dispatch via model_type, auto.py _FAMILIES)."""
    from transformers import Qwen2Config, Qwen2Model

    hf_cfg = Qwen2Config(
        vocab_size=101,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        intermediate_size=64,
        max_position_embeddings=64,
        rope_theta=10000.0,
        use_sliding_window=False,
    )
    model = Qwen2Model(hf_cfg).eval()
    hf_dict = hf_cfg.to_dict()
    cfg = jmistral.MistralConfig.from_hf_config(hf_dict)
    assert cfg.attention_bias  # inferred from model_type == 'qwen2'
    # use_sliding_window=False must win over the sliding_window value the
    # Qwen2 config carries anyway.
    assert cfg.sliding_window is None
    cfg.dtype = 'float32'
    params = jmistral.params_from_hf(_to_numpy_state(model), cfg)
    assert 'bias' in params['layers']['q']

    ids, mask = _rand_batch(np_rng, 2, 12, 101)
    with torch.no_grad():
        ref = model(
            input_ids=torch.tensor(ids.astype(np.int64)),
            attention_mask=torch.tensor(mask.astype(np.int64)),
        ).last_hidden_state.numpy()
    ours = np.asarray(jmistral.apply(params, cfg, ids, mask))
    np.testing.assert_allclose(ours, ref, atol=3e-5, rtol=1e-4)


def test_qwen2_decode_matches_prefill(np_rng):
    """The biased projections must flow through the paged decode path too:
    greedy decode_step logits == prefill logits at the same position."""
    cfg = jmistral.MistralConfig(
        vocab_size=64, hidden_size=16, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=32, attention_bias=True,
        dtype='float32',
    )
    params = jmistral.init(jax.random.PRNGKey(0), cfg)
    ids, mask = _rand_batch(np_rng, 1, 6, 64)
    hidden, k, v = jmistral.prefill(params, cfg, ids, mask)
    want = np.asarray(jmistral.logits(params, cfg, hidden))[0, -1]

    from distllm_tpu.generate.engine.engine import _write_prefill_all_layers

    bs, nb = 4, 8
    kshape = (cfg.num_layers, nb, bs, cfg.num_kv_heads, cfg.head_size)
    k_cache = jnp.zeros(kshape, jnp.float32)
    v_cache = jnp.zeros(kshape, jnp.float32)
    table = jnp.asarray([[1, 2, 0, 0]], jnp.int32)
    k_cache, v_cache = _write_prefill_all_layers(
        k_cache, v_cache, k, v, table, jnp.asarray([6], jnp.int32)
    )
    lg, _, _ = jmistral.decode_step(
        params, cfg, jnp.asarray(ids[:, -1]), jnp.asarray([5], jnp.int32),
        k_cache, v_cache, table, jnp.asarray([6], jnp.int32),
    )
    np.testing.assert_allclose(np.asarray(lg)[0], want, atol=2e-5)


def test_mistral_logits_and_prefill(np_rng):
    cfg = jmistral.MistralConfig(
        vocab_size=64,
        hidden_size=16,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        intermediate_size=32,
        dtype='float32',
    )
    params = jmistral.init(jax.random.PRNGKey(0), cfg)
    ids, mask = _rand_batch(np_rng, 2, 8, 64)
    hidden, k, v = jmistral.prefill(params, cfg, ids, mask)
    assert hidden.shape == (2, 8, 16)
    assert k.shape == (cfg.num_layers, 2, 8, cfg.num_kv_heads, cfg.head_size)
    lg = jmistral.logits(params, cfg, hidden)
    assert lg.shape == (2, 8, 64)
    assert lg.dtype == np.float32


def test_esm2_matches_hf(np_rng):
    from transformers import EsmConfig, EsmModel

    hf_cfg = EsmConfig(
        vocab_size=33,
        hidden_size=24,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=48,
        max_position_embeddings=128,
        position_embedding_type='rotary',
        token_dropout=True,
        mask_token_id=32,
        pad_token_id=1,
        emb_layer_norm_before=False,
    )
    model = EsmModel(hf_cfg, add_pooling_layer=False).eval()
    cfg = jesm.Esm2Config.from_hf_config(hf_cfg.to_dict())
    cfg.dtype = 'float32'
    params = jesm.params_from_hf(_to_numpy_state(model), cfg)

    ids, mask = _rand_batch(np_rng, 2, 10, 30, pad_from=[10, 7])
    ids[mask == 0] = 1  # ESM pad token
    with torch.no_grad():
        ref = model(
            input_ids=torch.tensor(ids.astype(np.int64)),
            attention_mask=torch.tensor(mask.astype(np.int64)),
        ).last_hidden_state.numpy()
    ours = np.asarray(jesm.apply(params, cfg, ids, mask))
    valid = mask.astype(bool)
    np.testing.assert_allclose(ours[valid], ref[valid], atol=3e-5, rtol=1e-4)


def test_bert_tp_sharding_matches_single_device():
    """TP over the 8-device virtual mesh == single-device numerics."""
    from distllm_tpu.parallel import make_mesh, shard_pytree
    from distllm_tpu.parallel.mesh import MeshSpec

    cfg = jbert.BertConfig(
        vocab_size=64,
        hidden_size=32,
        num_layers=2,
        num_heads=4,
        intermediate_size=64,
        max_position_embeddings=32,
        dtype='float32',
    )
    params = jbert.init(jax.random.PRNGKey(1), cfg)
    ids = np.arange(2 * 16).reshape(2, 16).astype(np.int32) % 64
    mask = np.ones((2, 16), np.int32)
    expected = np.asarray(jbert.apply(params, cfg, ids, mask))

    mesh = make_mesh(MeshSpec(data=2, model=4))
    sharded = shard_pytree(params, jbert.param_specs(cfg), mesh)
    fn = jax.jit(lambda p, i, m: jbert.apply(p, cfg, i, m))
    out = np.asarray(fn(sharded, ids, mask))
    np.testing.assert_allclose(out, expected, atol=1e-5, rtol=1e-5)


def test_mixtral_moe_mlp_matches_expert_loop(np_rng):
    """Dense-einsum routed MoE == explicit per-expert loop (fp32)."""
    from distllm_tpu.models import mixtral as jmix

    b, s, h, i, e, k = 2, 6, 16, 32, 4, 2
    r = np_rng
    x = r.standard_normal((b, s, h)).astype(np.float32)
    router = r.standard_normal((h, e)).astype(np.float32) * 0.1
    gate = r.standard_normal((e, h, i)).astype(np.float32) * 0.1
    up = r.standard_normal((e, h, i)).astype(np.float32) * 0.1
    down = r.standard_normal((e, i, h)).astype(np.float32) * 0.1

    out = np.asarray(jmix.moe_mlp(x, router, gate, up, down, k))

    # reference: loop over tokens and their top-k experts
    import scipy.special as sp

    probs = sp.softmax(x.reshape(-1, h) @ router, axis=-1)
    expected = np.zeros((b * s, h), np.float32)
    for t, row in enumerate(x.reshape(-1, h)):
        idx = np.argsort(-probs[t])[:k]
        w = probs[t, idx] / probs[t, idx].sum()
        for j, ei in enumerate(idx):
            hid = (row @ gate[ei]) * sp.expit(row @ gate[ei]) * (row @ up[ei])
            expected[t] += w[j] * (hid @ down[ei])
    np.testing.assert_allclose(
        out.reshape(-1, h), expected, atol=1e-4, rtol=1e-4
    )


def test_mixtral_matches_hf(np_rng):
    from transformers import MixtralConfig as HFMixtralConfig
    from transformers import MixtralModel

    hf_cfg = HFMixtralConfig(
        vocab_size=89,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        intermediate_size=48,
        num_local_experts=4,
        num_experts_per_tok=2,
        max_position_embeddings=64,
        rope_theta=10000.0,
        sliding_window=None,
    )
    model = MixtralModel(hf_cfg).eval()
    from distllm_tpu.models import mixtral as jmix

    cfg = jmix.MixtralConfig.from_hf_config(hf_cfg.to_dict())
    cfg.dtype = 'float32'
    params = jmix.params_from_hf(_to_numpy_state(model), cfg)

    ids, mask = _rand_batch(np_rng, 2, 10, 89)
    with torch.no_grad():
        ref = model(
            input_ids=torch.tensor(ids.astype(np.int64)),
            attention_mask=torch.tensor(mask.astype(np.int64)),
        ).last_hidden_state.numpy()
    ours = np.asarray(jmix.apply(params, cfg, ids, mask))
    np.testing.assert_allclose(ours, ref, atol=5e-5, rtol=1e-4)


def test_mixtral_serving_decode_matches_apply(np_rng):
    """Mixtral must flow through the shared paged serving machinery: the
    engine-facing prefill + greedy decode_step reproduce apply()'s
    next-token logits (MoE routing inside the decode layer loop)."""
    from distllm_tpu.generate.engine.engine import _write_prefill_all_layers
    from distllm_tpu.models import mixtral as jmix

    cfg = jmix.MixtralConfig(
        vocab_size=64, hidden_size=16, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=32, num_experts=4,
        experts_per_token=2, dtype='float32',
    )
    params = jmix.init(jax.random.PRNGKey(0), cfg)
    ids, mask = _rand_batch(np_rng, 1, 6, 64)
    hidden, k, v = jmix.prefill(params, cfg, ids, mask)
    # prefill's hidden must agree with the family's own apply().
    np.testing.assert_allclose(
        np.asarray(hidden), np.asarray(jmix.apply(params, cfg, ids, mask)),
        atol=1e-5,
    )
    want = np.asarray(jmix.logits(params, cfg, hidden))[0, -1]

    bs, nb = 4, 8
    kshape = (cfg.num_layers, nb, bs, cfg.num_kv_heads, cfg.head_size)
    k_cache = jnp.zeros(kshape, jnp.float32)
    v_cache = jnp.zeros(kshape, jnp.float32)
    table = jnp.asarray([[1, 2, 0, 0]], jnp.int32)
    k_cache, v_cache = _write_prefill_all_layers(
        k_cache, v_cache, k, v, table, jnp.asarray([6], jnp.int32)
    )
    for unroll in (False, True):
        lg, _, _ = jmix.decode_step(
            params, cfg, jnp.asarray(ids[:, -1]), jnp.asarray([5], jnp.int32),
            jnp.array(k_cache), jnp.array(v_cache), table,
            jnp.asarray([6], jnp.int32), layer_unroll=unroll,
        )
        np.testing.assert_allclose(np.asarray(lg)[0], want, atol=2e-5)
    # And the full engine serves it end to end.
    from distllm_tpu.generate.engine.engine import (
        EngineConfig,
        LLMEngine,
        SamplingParams,
    )

    class _Tok:
        eos_id = None

    engine = LLMEngine(
        cfg, params, _Tok(),
        EngineConfig(block_size=4, num_blocks=16, max_num_seqs=2,
                     max_model_len=32, prefill_min_bucket=8),
    )
    outs = engine.generate_ids(
        [[5, 9, 17], [3, 20]], SamplingParams(temperature=0.0, max_tokens=4)
    )
    engine.shutdown()
    assert all(len(o) == 4 for o in outs), outs


def test_mixtral_int8_serving(np_rng):
    """Weight-only int8 covers the 4-D expert banks (the bulk of an MoE
    model); the quantized engine must serve, and quantized decode logits
    must sit near the float ones (per-expert-channel scales)."""
    from distllm_tpu.generate.engine.engine import (
        EngineConfig,
        LLMEngine,
        SamplingParams,
    )
    from distllm_tpu.models import mixtral as jmix
    from distllm_tpu.ops.quantization import QTensor, quantize_pytree

    cfg = jmix.MixtralConfig(
        vocab_size=64, hidden_size=16, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=32, num_experts=4,
        experts_per_token=2, dtype='float32',
    )
    params = jmix.init(jax.random.PRNGKey(1), cfg)
    qparams = quantize_pytree(params, mode='int8', min_size=1)
    assert isinstance(qparams['layers']['gate']['kernel'], QTensor)

    ids, mask = _rand_batch(np_rng, 1, 5, 64)
    want = np.asarray(
        jmix.logits(params, cfg, jmix.apply(params, cfg, ids, mask))
    )[0, -1]
    got = np.asarray(
        jmix.logits(qparams, cfg, jmix.apply(qparams, cfg, ids, mask))
    )[0, -1]
    # int8 error is small but nonzero; the distributions must stay close.
    assert np.abs(got - want).max() < 0.05

    class _Tok:
        eos_id = None

    engine = LLMEngine(
        cfg, qparams, _Tok(),
        EngineConfig(block_size=4, num_blocks=16, max_num_seqs=2,
                     max_model_len=32, prefill_min_bucket=8),
    )
    outs = engine.generate_ids(
        [[5, 9, 17]], SamplingParams(temperature=0.0, max_tokens=4)
    )
    engine.shutdown()
    assert len(outs[0]) == 4


def test_mixtral_ep_sharding_matches_single_device():
    """EP x TP over the 8-device mesh == single-device numerics."""
    from distllm_tpu.models import mixtral as jmix
    from distllm_tpu.parallel import make_mesh, shard_pytree
    from distllm_tpu.parallel.mesh import MeshSpec

    cfg = jmix.MixtralConfig(
        vocab_size=64,
        hidden_size=16,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        intermediate_size=32,
        num_experts=4,
        experts_per_token=2,
        dtype='float32',
    )
    params = jmix.init(jax.random.PRNGKey(2), cfg)
    ids = np.arange(2 * 8).reshape(2, 8).astype(np.int32) % 64
    mask = np.ones((2, 8), np.int32)
    expected = np.asarray(jmix.apply(params, cfg, ids, mask))

    mesh = make_mesh(MeshSpec(data=1, seq=1, expert=4, model=2))
    sharded = shard_pytree(params, jmix.param_specs(cfg, params), mesh)
    fn = jax.jit(lambda p, i, m: jmix.apply(p, cfg, i, m))
    out = np.asarray(fn(sharded, ids, mask))
    np.testing.assert_allclose(out, expected, atol=1e-5, rtol=1e-5)


def test_gemma_matches_hf(np_rng):
    """Gemma-1: GeGLU, sqrt(hidden) embedding scale, (1+w) RMSNorm, tied
    embeddings — all config knobs on the shared family forward."""
    from transformers import GemmaConfig, GemmaModel

    from distllm_tpu.models import gemma as jgemma

    hf_cfg = GemmaConfig(
        vocab_size=101, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        intermediate_size=64, max_position_embeddings=64,
        hidden_act='gelu_pytorch_tanh', rms_norm_eps=1e-6,
    )
    model = GemmaModel(hf_cfg).eval()
    cfg = jgemma.GemmaConfig.from_hf_config(hf_cfg.to_dict())
    assert cfg.norm_plus_one and cfg.embedding_multiplier is not None
    cfg.dtype = 'float32'
    params = jgemma.params_from_hf(_to_numpy_state(model), cfg)

    ids, mask = _rand_batch(np_rng, 2, 12, 101)
    with torch.no_grad():
        ref = model(
            input_ids=torch.tensor(ids.astype(np.int64)),
            attention_mask=torch.tensor(mask.astype(np.int64)),
        ).last_hidden_state.numpy()
    ours = np.asarray(jgemma.apply(params, cfg, ids, mask))
    np.testing.assert_allclose(ours, ref, atol=3e-5, rtol=1e-4)


def test_gemma2_matches_hf(np_rng):
    """Gemma-2 adds sandwich norms, logit softcaps, query_pre_attn scaling
    and the alternating local/global window pattern; golden against HF
    incl. a sequence LONGER than the sliding window so the per-layer
    window masks are load-bearing."""
    from transformers import Gemma2Config, Gemma2ForCausalLM

    from distllm_tpu.models import gemma as jgemma

    hf_cfg = Gemma2Config(
        vocab_size=101, hidden_size=32, num_hidden_layers=4,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        intermediate_size=64, max_position_embeddings=96,
        hidden_activation='gelu_pytorch_tanh', rms_norm_eps=1e-6,
        query_pre_attn_scalar=16, sliding_window=8,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        attn_implementation='eager',  # softcap path; sdpa impl drops it
    )
    model = Gemma2ForCausalLM(hf_cfg).eval()
    cfg = jgemma.GemmaConfig.from_hf_config(hf_cfg.to_dict())
    assert cfg.post_norms and cfg.sliding_window_pattern == 'alternating'
    assert cfg.attn_logit_softcap == 50.0
    cfg.dtype = 'float32'
    params = jgemma.params_from_hf(_to_numpy_state(model), cfg)

    # seq 24 > window 8: window masks matter on the even (local) layers.
    ids, mask = _rand_batch(np_rng, 2, 24, 101)
    with torch.no_grad():
        ref = model(
            input_ids=torch.tensor(ids.astype(np.int64)),
            attention_mask=torch.tensor(mask.astype(np.int64)),
        ).logits.numpy()
    hidden = np.asarray(jgemma.apply(params, cfg, ids, mask))
    ours = np.asarray(jgemma.logits(params, cfg, hidden))
    np.testing.assert_allclose(ours, ref, atol=5e-5, rtol=1e-4)
    # The alternating pattern is load-bearing: all-global diverges.
    cfg_glob = cfg.model_copy(update={'sliding_window': None,
                                      'sliding_window_pattern': 'all'})
    glob_hidden = np.asarray(jgemma.apply(params, cfg_glob, ids, mask))
    assert np.abs(glob_hidden - hidden).max() > 1e-4
