"""Metrics registry, tracing, Timer shim, aggregation, scheduler wiring
(ISSUE 1 tentpole + satellites) and the flight-recorder layer (ISSUE 3)."""

from __future__ import annotations

import json
import math
import re
import time

import pytest

from distllm_tpu.observability import (
    Deadline,
    FlightRecorder,
    MetricsRegistry,
    RunRecord,
    StallWatchdog,
    TraceBuffer,
    dump_debug_bundle,
    get_registry,
    get_trace_buffer,
    log_buckets,
    log_event,
    span,
)
from distllm_tpu.observability.aggregate import (
    aggregate_lines,
    aggregate_logs,
    format_stats_table,
)
from distllm_tpu.timer import TimeLogger, TimeStats, Timer


# ------------------------------------------------------------------ metrics
def test_counter_semantics():
    registry = MetricsRegistry()
    c = registry.counter('test_events_total', 'events')
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)


def test_counter_labels_are_independent():
    registry = MetricsRegistry()
    c = registry.counter('test_by_kind_total', labelnames=('kind',))
    c.labels(kind='a').inc()
    c.labels(kind='a').inc()
    c.labels(kind='b').inc(5)
    assert c.labels(kind='a').value == 2
    assert c.labels(kind='b').value == 5
    with pytest.raises(ValueError):
        c.labels(wrong='x')
    with pytest.raises(ValueError):
        c.inc()  # labeled metric used without labels


def test_registry_get_or_create_and_conflicts():
    registry = MetricsRegistry()
    a = registry.counter('test_total', 'help')
    assert registry.counter('test_total') is a
    with pytest.raises(ValueError):
        registry.gauge('test_total')  # type conflict
    with pytest.raises(ValueError):
        registry.counter('test_total', labelnames=('x',))  # label conflict
    with pytest.raises(ValueError):
        registry.counter('bad name')


def test_gauge_semantics():
    registry = MetricsRegistry()
    g = registry.gauge('test_depth')
    g.set(10)
    g.inc(3)
    g.dec()
    assert g.value == 12


def test_histogram_semantics():
    registry = MetricsRegistry()
    h = registry.histogram('test_seconds', buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    assert h.count == 5
    assert h.sum == pytest.approx(56.05)
    text = registry.render()  # bucket counts are cumulative
    assert 'test_seconds_bucket{le="0.1"} 1' in text
    assert 'test_seconds_bucket{le="1"} 3' in text
    assert 'test_seconds_bucket{le="10"} 4' in text
    assert 'test_seconds_bucket{le="+Inf"} 5' in text
    with pytest.raises(ValueError):
        registry.histogram('test_bad', buckets=(1.0, 1.0))


def test_histogram_quantile_known_distribution():
    """Pin the linear-interpolation estimator on a known distribution:
    100 observations spread uniformly inside (0, 10] against buckets
    (1, 2, ..., 10) — every quantile is exact for uniform-in-bucket
    data, which is precisely the estimator's model."""
    registry = MetricsRegistry()
    h = registry.histogram(
        'test_quantile_seconds', buckets=tuple(float(b) for b in range(1, 11))
    )
    for i in range(100):
        h.observe((i + 0.5) / 10.0)  # 10 observations per bucket
    assert h.quantile(0.5) == pytest.approx(5.0)
    assert h.quantile(0.95) == pytest.approx(9.5)
    assert h.quantile(0.99) == pytest.approx(9.9)
    assert h.quantile(0.0) == pytest.approx(0.0)
    assert h.quantile(1.0) == pytest.approx(10.0)
    with pytest.raises(ValueError):
        h.quantile(1.5)


def test_histogram_quantile_edge_cases():
    registry = MetricsRegistry()
    h = registry.histogram('test_q_edge_seconds', buckets=(1.0, 10.0))
    assert h.quantile(0.5) is None  # empty histogram has no quantiles
    h.observe(0.5)
    # Single observation in the first bucket interpolates from 0.
    assert 0 < h.quantile(0.5) <= 1.0
    h.observe(100.0)  # +Inf bucket
    # Ranks landing in +Inf clamp to the highest finite edge.
    assert h.quantile(0.99) == pytest.approx(10.0)
    # Labeled children expose the same estimator.
    labeled = registry.histogram(
        'test_q_labeled_seconds', labelnames=('kind',), buckets=(1.0, 2.0)
    )
    labeled.labels(kind='a').observe(1.5)
    assert 1.0 <= labeled.labels(kind='a').quantile(0.5) <= 2.0
    # ALL mass in the +Inf bucket: every quantile clamps to the highest
    # finite edge — the estimator cannot invent an upper bound the
    # ladder never recorded.
    inf_only = registry.histogram('test_q_inf_seconds', buckets=(1.0, 10.0))
    inf_only.observe(50.0)
    inf_only.observe(500.0)
    for q in (0.0, 0.5, 0.99, 1.0):
        assert inf_only.quantile(q) == pytest.approx(10.0)
    # Zero-delta interval (two identical cumulative snapshots — the
    # history ring's idle tick): None, never a division.
    from distllm_tpu.observability import quantile_from_cumulative

    before = inf_only.cumulative_counts()
    delta = [a - b for a, b in zip(inf_only.cumulative_counts(), before)]
    assert quantile_from_cumulative(inf_only.buckets, delta, 0.5) is None


def test_quantile_from_cumulative_delta_isolates_window():
    """The loadgen pattern: difference two cumulative_counts() snapshots
    to get quantiles over only the observations in between."""
    from distllm_tpu.observability import quantile_from_cumulative

    registry = MetricsRegistry()
    h = registry.histogram('test_q_delta_seconds', buckets=(1.0, 2.0, 4.0))
    h.observe(0.5)  # pre-window noise (a warmup request)
    before = h.cumulative_counts()
    for _ in range(10):
        h.observe(3.0)  # the measured window: all in bucket (2, 4]
    delta = [a - b for a, b in zip(h.cumulative_counts(), before)]
    assert sum(
        n for n in delta
    ) == 10 or delta[-1] == 10  # cumulative: final entry counts all
    p50 = quantile_from_cumulative(h.buckets, delta, 0.5)
    assert 2.0 < p50 <= 4.0  # the warmup 0.5 s observation is excluded
    assert quantile_from_cumulative(h.buckets, [0, 0, 0, 0], 0.5) is None


def test_log_buckets_ladder():
    buckets = log_buckets(1e-3, 10.0, per_decade=1)
    assert buckets == (0.001, 0.01, 0.1, 1.0, 10.0)
    assert list(buckets) == sorted(buckets)
    with pytest.raises(ValueError):
        log_buckets(0, 1)


def test_prometheus_exposition_format():
    registry = MetricsRegistry()
    c = registry.counter('app_requests_total', 'requests', ('path',))
    c.labels(path='/x "quoted"\nline').inc()
    registry.gauge('app_depth', 'depth').set(4)
    h = registry.histogram('app_latency_seconds', 'latency', buckets=(1.0,))
    h.observe(0.5)
    text = registry.render()
    assert '# HELP app_requests_total requests' in text
    assert '# TYPE app_requests_total counter' in text
    # Label values escape backslash/quote/newline.
    assert 'app_requests_total{path="/x \\"quoted\\"\\nline"} 1' in text
    assert 'app_depth 4' in text
    assert 'app_latency_seconds_bucket{le="1"} 1' in text
    assert 'app_latency_seconds_bucket{le="+Inf"} 1' in text
    assert 'app_latency_seconds_sum 0.5' in text
    assert 'app_latency_seconds_count 1' in text
    sample_re = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? (\+Inf|-Inf|[0-9.eE+-]+)$'
    )
    for line in text.strip().splitlines():
        if not line.startswith('#'):
            assert sample_re.match(line), line


# ------------------------------------------------------------------ tracing
def test_span_nesting_and_status():
    buffer = TraceBuffer()
    with span('outer', buffer=buffer) as outer:
        with span('inner', 'tag-1', buffer=buffer) as inner:
            assert inner.parent_id == outer.span_id
    spans = buffer.snapshot()
    assert [s.name for s in spans] == ['inner', 'outer']  # close order
    assert all(s.status == 'ok' for s in spans)
    assert spans[0].duration_s >= 0

    with pytest.raises(RuntimeError, match='boom'):
        with span('failing', buffer=buffer):
            raise RuntimeError('boom')
    failed = buffer.snapshot()[-1]
    assert failed.status == 'error'
    assert 'boom' in failed.error


def test_trace_ring_eviction_and_dump(tmp_path):
    buffer = TraceBuffer(capacity=3)
    for i in range(5):
        with span(f's{i}', buffer=buffer):
            pass
    assert len(buffer) == 3
    assert buffer.total_recorded == 5
    assert [s.name for s in buffer.snapshot()] == ['s2', 's3', 's4']
    assert [s.name for s in buffer.snapshot(limit=2)] == ['s3', 's4']

    out = tmp_path / 'traces.jsonl'
    assert buffer.dump_jsonl(out) == 3
    records = [json.loads(line) for line in out.read_text().splitlines()]
    assert [r['name'] for r in records] == ['s2', 's3', 's4']
    assert all(r['status'] == 'ok' for r in records)
    assert all(r['duration_s'] is not None for r in records)


def test_request_scope_stamps_spans_and_nests():
    from distllm_tpu.observability import (
        current_request_id,
        request_scope,
    )

    buffer = TraceBuffer()
    assert current_request_id() is None
    with request_scope('req-42'):
        assert current_request_id() == 'req-42'
        with span('scoped-work', buffer=buffer) as s:
            assert s.attributes['request_id'] == 'req-42'
        with request_scope('req-inner'):
            assert current_request_id() == 'req-inner'
        assert current_request_id() == 'req-42'
    assert current_request_id() is None
    # None scope is a no-op (optional ids pass through unconditionally).
    with request_scope(None):
        assert current_request_id() is None
        with span('unscoped-work', buffer=buffer) as s:
            assert 'request_id' not in s.attributes
    # An explicit attribute wins over the scope.
    with request_scope('req-outer'):
        with span('explicit', buffer=buffer, request_id='req-pinned') as s:
            assert s.attributes['request_id'] == 'req-pinned'
    # Spans record their opening thread (the Perfetto track key).
    import threading

    recorded = buffer.snapshot()[-1]
    assert recorded.thread_id == threading.get_ident()
    assert recorded.to_dict()['thread_id'] == recorded.thread_id


# --------------------------------------------------------------- Timer shim
def test_timer_emits_legacy_line_and_span(capsys):
    buffer = get_trace_buffer()
    before = buffer.total_recorded
    with Timer('shim-stage', 'file-9'):
        pass
    out = capsys.readouterr().out
    stats = TimeLogger().parse_lines(out)  # legacy format still parses
    assert stats[('shim-stage', 'file-9')].count == 1
    assert buffer.total_recorded == before + 1
    recorded = buffer.snapshot()[-1]
    assert recorded.name == 'shim-stage'
    assert recorded.tags == ('shim-stage', 'file-9')
    assert recorded.status == 'ok'


def test_timer_tags_error_spans(capsys):
    buffer = get_trace_buffer()
    with pytest.raises(ValueError):
        with Timer('doomed-stage'):
            raise ValueError('nope')
    # Legacy line still emitted for failed work (scrapers expect it)...
    assert '[timer] tags=doomed-stage' in capsys.readouterr().out
    # ...but the span distinguishes the outcome.
    recorded = buffer.snapshot()[-1]
    assert recorded.status == 'error'
    assert 'nope' in recorded.error


def test_timer_observes_stage_histogram():
    h = get_registry().get('distllm_stage_duration_seconds')
    child = h.labels(stage='histo-stage', status='ok')
    before = child.count
    with Timer('histo-stage', echo=False):
        pass
    assert child.count == before + 1


def test_timer_restart_without_stop_does_not_leak_stack():
    from distllm_tpu.observability import tracing

    t = Timer('restarted', echo=False)
    t.start()
    t.start()  # restart with no stop(): the stale span must be abandoned
    t.stop()
    assert tracing._stack() == []
    with span('after-restart') as s:
        assert s.parent_id is None


def test_timer_never_started_raises():
    t = Timer('idle')
    with pytest.raises(RuntimeError):
        t.elapsed_s
    with pytest.raises(RuntimeError):
        t.stop()


def test_timestats_percentiles():
    stats = TimeStats(tags=('x',), elapsed_s=[4.0, 1.0, 3.0, 2.0])
    assert stats.p50_s == 2.0
    assert stats.p95_s == 4.0
    assert stats.max_s == 4.0
    empty = TimeStats(tags=('y',))
    assert empty.p50_s == 0.0 and empty.p95_s == 0.0 and empty.max_s == 0.0
    single = TimeStats(tags=('z',), elapsed_s=[7.0])
    assert single.p50_s == single.p95_s == single.max_s == 7.0


# -------------------------------------------------------------- aggregation
def _fake_log(tag: str, values: list[float]) -> str:
    return '\n'.join(
        f'[timer] tags={tag} elapsed_s={v:.9f} start_ns=0 end_ns=1'
        for v in values
    )


def test_aggregate_multi_host_logs(tmp_path):
    log_a = tmp_path / 'host-a.log'
    log_b = tmp_path / 'host-b.log'
    log_a.write_text(_fake_log('embed,f1', [1.0, 2.0]))
    log_b.write_text(_fake_log('embed,f1', [3.0]) + '\n' + _fake_log('write', [0.5]))
    merged = aggregate_logs([log_a, log_b])
    assert merged[('embed', 'f1')].count == 3
    assert merged[('embed', 'f1')].total_s == pytest.approx(6.0)
    assert merged[('write',)].count == 1

    table = format_stats_table(merged)
    lines = table.splitlines()
    assert lines[0].split()[:2] == ['tags', 'count']
    assert 'p50_s' in lines[0] and 'p95_s' in lines[0] and 'max_s' in lines[0]
    assert lines[2].startswith('embed,f1')  # sorted by total desc

    assert aggregate_lines([]) == {}


def test_aggregate_merges_span_jsonl_with_timer_lines(tmp_path):
    # A [timer] log from one host...
    timer_log = tmp_path / 'host-a.log'
    timer_log.write_text(_fake_log('embed,f1', [1.0]))
    # ...and a span-JSONL dump from another (Timer-shim spans carry the
    # same tags, so both formats merge into ONE stats row).
    buffer = TraceBuffer()
    with span('embed', 'embed', 'f1', buffer=buffer):
        pass
    with span('solo-span', buffer=buffer):
        pass
    span_dump = tmp_path / 'host-b-traces.jsonl'
    buffer.dump_jsonl(span_dump)
    # Flight-ring dumps merge too (keyed by record kind)...
    flight = FlightRecorder()
    flight.record('decode', duration_s=0.25)
    flight.record('decode', duration_s=0.35)
    flight.record('request', ttft_s=0.1)  # no duration_s -> skipped
    flight_dump = tmp_path / 'host-b-flight.jsonl'
    flight.dump_jsonl(flight_dump)
    # ...and torn lines (killed process mid-write) are skipped.
    with open(span_dump, 'a') as handle:
        handle.write('{"name": "torn", "duration_s"')

    merged = aggregate_logs([timer_log, span_dump, flight_dump])
    assert merged[('embed', 'f1')].count == 2  # timer line + span record
    assert merged[('solo-span',)].count == 1
    assert merged[('decode',)].count == 2
    assert merged[('decode',)].total_s == pytest.approx(0.6)
    assert ('torn',) not in merged


def test_aggregate_dedups_same_measurement_across_formats(tmp_path, capsys):
    """timer.Timer emits BOTH a [timer] line and a span for every timed
    region; passing a worker's stdout log AND its trace dump must not
    double count the measurement (same tags + same clock bounds)."""
    buffer = get_trace_buffer()
    with Timer('dedup-stage', 'f7'):
        pass
    timer_log = tmp_path / 'worker.log'
    timer_log.write_text(capsys.readouterr().out)
    span_dump = tmp_path / 'traces.jsonl'
    recorded = buffer.snapshot()[-1]
    span_dump.write_text(json.dumps(recorded.to_dict()) + '\n')

    merged = aggregate_logs([timer_log, span_dump])
    assert merged[('dedup-stage', 'f7')].count == 1


def test_aggregate_table_reports_cross_host_percentiles(tmp_path):
    """The table carries p50/p95/p99 computed over the MERGED multi-host
    distribution, not per-file."""
    log_a = tmp_path / 'a.log'
    log_b = tmp_path / 'b.log'
    log_a.write_text(_fake_log('embed', [1.0] * 50))
    log_b.write_text(_fake_log('embed', [2.0] * 49 + [10.0]))
    merged = aggregate_logs([log_a, log_b])
    stats = merged[('embed',)]
    assert stats.count == 100
    assert stats.p50_s == pytest.approx(1.0)
    assert stats.p99_s == pytest.approx(2.0)
    assert stats.max_s == pytest.approx(10.0)
    table = format_stats_table(merged)
    header = table.splitlines()[0]
    assert 'p50_s' in header and 'p95_s' in header and 'p99_s' in header


def test_aggregate_cli_writes_combined_perfetto(tmp_path, capsys):
    """--perfetto merges flight/span JSONL dumps from multiple hosts into
    one valid trace with a process group per input file."""
    import json as _json

    from distllm_tpu.observability import validate_trace_events
    from distllm_tpu.observability.aggregate import main

    flight = FlightRecorder()
    flight.record('decode', duration_s=0.25, batch=2, tokens=32)
    flight.record(
        'request', e2e_s=0.5, ttft_s=0.1, request_id=0, output_tokens=8
    )
    flight_dump = tmp_path / 'host-a-flight.jsonl'
    flight.dump_jsonl(flight_dump)
    buffer = TraceBuffer()
    with span('host-b-work', buffer=buffer):
        pass
    span_dump = tmp_path / 'host-b-traces.jsonl'
    buffer.dump_jsonl(span_dump)
    out = tmp_path / 'combined.json'
    assert main(
        [str(flight_dump), str(span_dump), '--perfetto', str(out)]
    ) == 0
    captured = capsys.readouterr().out
    assert 'combined.json' in captured
    doc = _json.loads(out.read_text())
    assert validate_trace_events(doc) == []
    pids = {e['pid'] for e in doc['traceEvents']}
    assert pids == {1, 2}
    names = {e['name'] for e in doc['traceEvents'] if e.get('ph') != 'M'}
    assert 'decode' in names and 'host-b-work' in names


def test_aggregate_cli_entry_point(tmp_path, capsys):
    from distllm_tpu.observability.aggregate import main

    log = tmp_path / 'worker.log'
    log.write_text(_fake_log('cli-stage', [1.0, 3.0]))
    assert main([str(log)]) == 0
    out = capsys.readouterr().out
    assert 'cli-stage' in out and 'p95_s' in out
    # No parseable telemetry in the inputs -> nonzero exit.
    empty = tmp_path / 'empty.log'
    empty.write_text('nothing here\n')
    assert main([str(empty)]) == 1


def test_aggregate_runs_as_module(tmp_path):
    """``python -m distllm_tpu.observability.aggregate`` is the operator
    CLI — keep the module executable."""
    import subprocess
    import sys

    log = tmp_path / 'worker.log'
    log.write_text(_fake_log('mod-stage', [2.0]))
    proc = subprocess.run(
        [
            sys.executable, '-m', 'distllm_tpu.observability.aggregate',
            str(log),
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert 'mod-stage' in proc.stdout


# ------------------------------------------------------- flight recorder
def test_flight_recorder_ring_and_dump(tmp_path):
    recorder = FlightRecorder(capacity=3)
    for i in range(5):
        recorder.record('decode', step=i, duration_s=0.01)
    assert len(recorder) == 3
    assert recorder.total_recorded == 5
    steps = [r['step'] for r in recorder.snapshot()]
    assert steps == [2, 3, 4]
    assert [r['step'] for r in recorder.snapshot(limit=2)] == [3, 4]
    assert all(r['kind'] == 'decode' for r in recorder.snapshot())
    assert all('t_wall' in r for r in recorder.snapshot())

    out = tmp_path / 'flight.jsonl'
    assert recorder.dump_jsonl(out) == 3
    records = [json.loads(line) for line in out.read_text().splitlines()]
    assert [r['step'] for r in records] == [2, 3, 4]


def test_debug_bundle_contents(tmp_path):
    recorder = FlightRecorder()
    recorder.record('prefill', duration_s=0.5, batch=4)
    with span('bundle-span'):
        pass
    paths = dump_debug_bundle(
        tmp_path / 'bundle', reason='unit test', recorder=recorder,
        extra={'stage': 'gen'},
    )
    assert set(paths) >= {'flight', 'metrics', 'traces', 'meta'}
    flight = [
        json.loads(line)
        for line in (tmp_path / 'bundle' / 'flight.jsonl').read_text().splitlines()
    ]
    assert flight[0]['kind'] == 'prefill'
    assert 'distllm_engine_steps_total' in (
        tmp_path / 'bundle' / 'metrics.prom'
    ).read_text()
    meta = json.loads((tmp_path / 'bundle' / 'meta.json').read_text())
    assert meta['reason'] == 'unit test'
    assert meta['stage'] == 'gen'


def test_stall_watchdog_fires_on_stall_and_respects_progress():
    recorder = FlightRecorder()
    fired = []
    dog = StallWatchdog(
        0.2,
        progress_fn=lambda: recorder.total_recorded,
        on_stall=fired.append,
        poll_s=0.05,
    )
    with dog:
        # Keep making progress: the dog must stay quiet.
        for _ in range(4):
            recorder.record('decode')
            time.sleep(0.08)
        assert fired == []
        # Stop progressing: the dog fires exactly once (max_fires=1).
        time.sleep(0.6)
    assert len(fired) == 1
    assert dog.fired == 1


def test_stall_watchdog_beat_counts_as_progress():
    fired = []
    dog = StallWatchdog(
        0.2, progress_fn=lambda: 0, on_stall=fired.append, poll_s=0.05
    )
    with dog:
        for _ in range(4):
            dog.beat()
            time.sleep(0.08)
        assert fired == []


def test_stall_watchdog_default_dumps_bundle(tmp_path):
    recorder_value = [0]
    dog = StallWatchdog(
        0.15,
        progress_fn=lambda: recorder_value[0],
        bundle_dir=tmp_path / 'stall',
        poll_s=0.05,
        name='unit-dog',
    )
    from distllm_tpu.observability import instruments

    stalls_before = instruments.WATCHDOG_STALLS.value
    with dog:
        time.sleep(0.5)
    assert (tmp_path / 'stall' / 'meta.json').exists()
    assert instruments.WATCHDOG_STALLS.value == stalls_before + 1


# ------------------------------------------------------------- run record
def test_run_record_incremental_and_snapshot(tmp_path):
    record = RunRecord(tmp_path / 'BENCH_partial.jsonl')
    record.record('embed', {'metric': 'emb/s', 'value': 100.0})
    # The JSONL line is durable immediately (fsync'd append).
    lines = (tmp_path / 'BENCH_partial.jsonl').read_text().splitlines()
    assert len(lines) == 1
    record.record('gen', {'gen_value': 800.0})
    assert record.stages() == ['embed', 'gen']
    composed = record.compose()
    assert composed == {'metric': 'emb/s', 'value': 100.0, 'gen_value': 800.0}
    # Snapshot is the composed view, rewritten atomically per record().
    snapshot = json.loads(record.snapshot_path.read_text())
    assert snapshot == composed
    # A fresh reader (crash recovery) replays the same state from disk.
    replay = RunRecord(tmp_path / 'BENCH_partial.jsonl')
    assert replay.compose() == composed


def test_run_record_skips_torn_final_line(tmp_path):
    record = RunRecord(tmp_path / 'rec.jsonl')
    record.record('embed', {'value': 1.0})
    with open(record.path, 'a') as handle:
        handle.write('{"stage": "gen", "fragment": {"gen_va')  # torn write
    assert record.stages() == ['embed']
    assert record.compose() == {'value': 1.0}


# --------------------------------------------------------------- deadline
def test_deadline_budgets_and_expiry():
    deadline = Deadline(100.0, reserve_s=10.0)
    assert not deadline.expired
    # Nominal budget clamps to remaining (90s window left).
    assert deadline.budget(3600.0) <= 90.0
    assert deadline.budget(5.0) == 5.0
    # Below the floor: skip signal.
    assert deadline.budget(3600.0, floor_s=1000.0) == 0.0
    tiny = Deadline(0.05, reserve_s=0.0)
    time.sleep(0.1)
    assert tiny.expired
    assert tiny.budget(10.0) == 0.0
    with pytest.raises(ValueError):
        Deadline(0)


# ---------------------------------------------------------------- log_event
def test_log_event_prints_and_counts(capsys):
    counter = get_registry().get('distllm_log_messages_total')
    child = counter.labels(component='test-comp')
    before = child.value
    log_event('[test] hello', component='test-comp')
    assert capsys.readouterr().out == '[test] hello\n'
    assert child.value == before + 1


# --------------------------------------------------- scheduler instrumentation
def test_instrumented_scheduler_publishes_metrics():
    from distllm_tpu.generate.engine.scheduler import (
        InstrumentedScheduler,
        PyScheduler,
    )
    from distllm_tpu.observability import instruments

    sched = InstrumentedScheduler(
        PyScheduler(num_blocks=9, block_size=4, max_num_seqs=2),
        num_blocks=9,
    )
    assert instruments.KV_BLOCKS_TOTAL.value == 8
    admitted_before = instruments.SCHED_ADMITTED.value
    deferred_before = instruments.SCHED_DEFERRED.value

    sched.add(0, 4)
    sched.add(1, 4)
    sched.add(2, 4)
    assert instruments.SCHED_QUEUE_DEPTH.value == 3
    assert sched.admit_next() == 0
    assert sched.admit_next() == 1
    assert sched.admit_next() is None  # no free slot -> deferred
    assert instruments.SCHED_ADMITTED.value == admitted_before + 2
    assert instruments.SCHED_DEFERRED.value == deferred_before + 1
    assert instruments.SCHED_RUNNING.value == 2
    assert instruments.SCHED_QUEUE_DEPTH.value == 1
    assert instruments.KV_BLOCKS_IN_USE.value == 4  # 2 blocks per request
    assert instruments.KV_OCCUPANCY.value == pytest.approx(0.5)

    sched.finish(0)
    sched.finish(1)
    sched.finish(2)
    assert instruments.SCHED_RUNNING.value == 0
    assert instruments.KV_BLOCKS_IN_USE.value == 0


def test_instrumented_scheduler_counts_preemptions():
    from distllm_tpu.generate.engine.scheduler import (
        InstrumentedScheduler,
        PyScheduler,
    )
    from distllm_tpu.observability import instruments

    sched = InstrumentedScheduler(
        PyScheduler(num_blocks=5, block_size=2, max_num_seqs=2),
        num_blocks=5,
    )
    preempt_before = instruments.SCHED_PREEMPTIONS.value
    sched.add(0, 2)
    sched.add(1, 2)
    assert sched.admit_next() == 0
    assert sched.admit_next() == 1
    # Grow both sequences until the pool runs dry -> youngest preempted.
    for _ in range(4):
        sched.append_token(0)
        sched.append_token(1)
    preempted = sched.prepare_decode(2)
    assert preempted == [1]
    assert instruments.SCHED_PREEMPTIONS.value == preempt_before + 1


# ------------------------------------------------------- known-series catalog
def test_instruments_catalog_renders_engine_series():
    """The full serving schema is present in a scrape before any traffic."""
    from distllm_tpu.observability import render_prometheus

    text = render_prometheus()
    for name in (
        'distllm_engine_generated_tokens_total',
        'distllm_engine_prefill_dispatches_total',
        'distllm_engine_decode_windows_total',
        'distllm_kv_cache_blocks_total',
        'distllm_kv_cache_occupancy_ratio',
        'distllm_scheduler_queue_depth',
        'distllm_scheduler_preemptions_total',
        'distllm_http_requests_in_flight',
    ):
        assert f'# TYPE {name} ' in text, name


def test_histogram_inf_bucket_formatting():
    registry = MetricsRegistry()
    h = registry.histogram('edge_seconds', buckets=(1.0,))
    h.observe(math.inf)  # lands in +Inf bucket without error
    assert h.count == 1
    assert 'edge_seconds_bucket{le="+Inf"} 1' in registry.render()
