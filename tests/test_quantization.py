"""Weight-only quantization: round-trip error bounds, pytree policy, and
end-to-end encoder closeness (the TPU-native analogue of the reference's
bitsandbytes NF4 path, ``distllm/embed/encoders/auto.py:46-56``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distllm_tpu.ops.quantization import (
    QTensor,
    dequantize_pytree,
    quantize_int8,
    quantize_nf4,
    quantize_pytree,
    quantized_nbytes,
)


@pytest.fixture(scope='module')
def np_rng():
    return np.random.default_rng(7)


def test_int8_round_trip_error_bound(np_rng):
    w = np_rng.normal(size=(64, 128)).astype(np.float32)
    qt = quantize_int8(w, out_dtype='float32')
    restored = np.asarray(qt.dequantize())
    # Per-channel symmetric quantization: error <= scale/2 per element.
    scale = np.abs(w).max(axis=0) / 127.0
    assert np.all(np.abs(restored - w) <= scale[None, :] * 0.5 + 1e-7)


def test_int8_stacked_layers_per_layer_scales(np_rng):
    """3-D [L, in, out] kernels (common.stack_layers) quantize per layer."""
    w = np.stack([
        np_rng.normal(size=(32, 16)).astype(np.float32),
        100.0 * np_rng.normal(size=(32, 16)).astype(np.float32),
    ])
    qt = quantize_int8(w, out_dtype='float32')
    restored = np.asarray(qt.dequantize())
    assert restored.shape == w.shape
    # Layer 0's error must be set by layer 0's own scale, not layer 1's
    # 100x larger range.
    scale0 = np.abs(w[0]).max(axis=0) / 127.0
    assert np.all(np.abs(restored[0] - w[0]) <= scale0[None, :] * 0.5 + 1e-7)


def test_nf4_round_trip_reasonable(np_rng):
    w = np_rng.normal(size=(32, 64)).astype(np.float32)
    qt = quantize_nf4(w, block_size=64, out_dtype='float32')
    restored = np.asarray(qt.dequantize())
    assert restored.shape == w.shape
    # NF4 is 4-bit: expect high correlation, not tight elementwise error.
    corr = np.corrcoef(w.ravel(), restored.ravel())[0, 1]
    assert corr > 0.98
    # Exactly-zero weights hit codebook level 7 exactly.
    wz = np.zeros((8, 8), dtype=np.float32)
    assert np.all(np.asarray(quantize_nf4(wz).dequantize()) == 0.0)


def test_nf4_padding_tail_block(np_rng):
    w = np_rng.normal(size=(7, 33)).astype(np.float32)  # 231 % 64 != 0
    qt = quantize_nf4(w, block_size=64, out_dtype='float32')
    restored = np.asarray(qt.dequantize())
    assert restored.shape == w.shape
    assert np.corrcoef(w.ravel(), restored.ravel())[0, 1] > 0.98


def test_quantize_pytree_policy(np_rng):
    params = {
        'embeddings': {'word': np_rng.normal(size=(128, 64)).astype(np.float32)},
        'layer0': {
            'dense': np_rng.normal(size=(128, 128)).astype(np.float32),
            'norm_scale': np.ones((128,), dtype=np.float32),
            'tiny': np_rng.normal(size=(4, 4)).astype(np.float32),
        },
    }
    params['layer0']['router'] = {
        'kernel': np_rng.normal(size=(128, 128)).astype(np.float32)
    }
    qparams = quantize_pytree(params, mode='int8', min_size=1024)
    assert isinstance(qparams['layer0']['dense'], QTensor)
    # Embedding tables, norms, small leaves, and MoE routers stay float
    # (routers feed moe_mlp's raw einsums and are precision-sensitive).
    assert isinstance(qparams['embeddings']['word'], np.ndarray)
    assert isinstance(qparams['layer0']['norm_scale'], np.ndarray)
    assert isinstance(qparams['layer0']['tiny'], np.ndarray)
    assert isinstance(qparams['layer0']['router']['kernel'], np.ndarray)
    q_bytes, _ = quantized_nbytes(qparams)
    assert 0 < q_bytes < 128 * 128 * 4


@pytest.mark.parametrize('mode', ['int8', 'nf4'])
def test_stacked_qtensor_dequantizes_inside_scan(np_rng, mode):
    """A stacked [L, in, out] QTensor rides lax.scan over layers: scan
    slices the codes/scales per layer and dequantize() restores THAT
    layer's [in, out] weight inside the loop body — the memory-safe
    serving path (whole-tree dequant OOMed 7B int8, BENCH r3)."""
    L, n_in, n_out = 3, 32, 48
    w = np_rng.normal(size=(L, n_in, n_out)).astype(np.float32)
    qt = (quantize_int8(w) if mode == 'int8'
          else quantize_nf4(w, block_size=16))

    def body(carry, layer_qt):
        assert layer_qt.q.ndim == qt.q.ndim - 1  # scan really sliced it
        return carry, layer_qt.dequantize()

    _, per_layer = jax.lax.scan(body, jnp.zeros(()), qt)
    assert per_layer.shape == (L, n_in, n_out)
    for li in range(L):
        want = (quantize_int8(w[li]) if mode == 'int8'
                else quantize_nf4(w[li], block_size=16)).dequantize()
        np.testing.assert_array_equal(
            np.asarray(per_layer[li]), np.asarray(want)
        )


def test_quantize_pytree_delete_source_streams(np_rng):
    """delete_source frees each replaced device leaf; kept leaves survive."""
    params = {
        'dense': jnp.asarray(np_rng.normal(size=(128, 128)).astype(np.float32)),
        'norm_scale': jnp.ones((128,), dtype=jnp.float32),
    }
    qparams = quantize_pytree(params, mode='int8', min_size=1024,
                              delete_source=True)
    assert isinstance(qparams['dense'], QTensor)
    assert params['dense'].is_deleted()
    # Pass-through leaves are NOT deleted and remain usable.
    assert not params['norm_scale'].is_deleted()
    np.testing.assert_allclose(np.asarray(qparams['norm_scale']), 1.0)


def test_nf4_storage_is_under_5_bits_per_weight(np_rng):
    w = np_rng.normal(size=(256, 256)).astype(np.float32)
    qt = quantize_nf4(w, block_size=64)
    assert qt.nbytes * 8 / w.size < 5.0


def test_dequant_matmul_inside_jit(np_rng):
    w = np_rng.normal(size=(64, 32)).astype(np.float32)
    x = np_rng.normal(size=(8, 64)).astype(np.float32)
    qt = quantize_int8(w, out_dtype='float32')

    @jax.jit
    def f(qt, x):
        return x @ qt.dequantize()

    got = np.asarray(f(qt, jnp.asarray(x)))
    want = x @ w
    np.testing.assert_allclose(got, want, atol=0.2, rtol=0.05)


def test_quantized_pytree_through_jit_boundary(np_rng):
    """QTensor is a pytree node: it can cross jit as part of params."""
    params = {'w': quantize_nf4(np_rng.normal(size=(64, 64)).astype(np.float32),
                                out_dtype='float32')}

    @jax.jit
    def f(params, x):
        return x @ dequantize_pytree(params)['w']

    x = np_rng.normal(size=(4, 64)).astype(np.float32)
    out = np.asarray(f(params, jnp.asarray(x)))
    assert out.shape == (4, 64)
    assert np.isfinite(out).all()


def test_bert_quantized_forward_close(np_rng):
    """Quantized (int8) encoder output stays close to full precision."""
    from distllm_tpu.models import bert as jbert

    cfg = jbert.BertConfig(
        vocab_size=97,
        hidden_size=32,
        num_layers=2,
        num_heads=4,
        intermediate_size=64,
        max_position_embeddings=48,
        dtype='float32',
    )
    params = jbert.init(jax.random.PRNGKey(0), cfg)
    ids = np_rng.integers(0, 97, size=(2, 16)).astype(np.int32)
    mask = np.ones_like(ids)

    full = np.asarray(jbert.apply(params, cfg, ids, mask))
    qparams = quantize_pytree(params, mode='int8', min_size=512,
                              out_dtype='float32')
    n_quantized = sum(
        isinstance(leaf, QTensor)
        for leaf in jax.tree_util.tree_leaves(
            qparams, is_leaf=lambda x: isinstance(x, QTensor)
        )
    )
    # Stacked 3-D layer kernels MUST be quantized — a policy regression that
    # silently skips them would make this test vacuous.
    assert n_quantized >= 4, n_quantized
    quant = np.asarray(
        jax.jit(
            lambda p, i, m: jbert.apply(dequantize_pytree(p), cfg, i, m)
        )(qparams, ids, mask)
    )
    cos = np.sum(full * quant) / (
        np.linalg.norm(full) * np.linalg.norm(quant)
    )
    assert cos > 0.999


def test_quantized_params_shard_over_mesh(np_rng):
    """TP + quantization: QTensor leaves replicate, float leaves shard."""
    import jax.numpy as jnp  # noqa: F811

    from distllm_tpu.models import mistral
    from distllm_tpu.parallel.mesh import MeshSpec, make_mesh
    from distllm_tpu.parallel.sharding import shard_pytree

    cfg = mistral.MistralConfig(
        vocab_size=64,
        hidden_size=32,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        intermediate_size=64,
        dtype='float32',
    )
    params = mistral.init(jax.random.PRNGKey(0), cfg)
    qparams = quantize_pytree(params, mode='int8', min_size=512,
                              out_dtype='float32')
    assert any(
        isinstance(leaf, QTensor)
        for leaf in jax.tree_util.tree_leaves(
            qparams, is_leaf=lambda x: isinstance(x, QTensor)
        )
    )
    mesh = make_mesh(MeshSpec(data=1, model=2), devices=jax.devices()[:2])
    sharded = shard_pytree(qparams, mistral.param_specs(cfg, qparams), mesh)

    ids = np.array([[3, 1, 4, 1]], dtype=np.int32)
    mask = np.ones_like(ids)
    with mesh:
        out = jax.jit(
            lambda p, i, m: mistral.apply(dequantize_pytree(p), cfg, i, m)
        )(sharded, ids, mask)
    want = np.asarray(
        mistral.apply(dequantize_pytree(qparams), cfg, ids, mask)
    )
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-5, rtol=1e-5)


def test_int8_expert_bank_roundtrip():
    """4-D [L, E, in, out] expert banks quantize with per-(layer, expert,
    channel) scales and dequantize close to the source."""
    from distllm_tpu.ops.quantization import quantize_int8

    rng = np.random.default_rng(0)
    w = rng.normal(size=(2, 3, 16, 8)).astype(np.float32)
    w[1, 2] *= 50.0  # one expert with a wild dynamic range
    qt = quantize_int8(w)
    assert qt.scale.shape == (2, 3, 1, 8)
    err = np.abs(np.asarray(qt.dequantize(), np.float32) - w)
    # Per-expert scales keep the mild experts accurate despite the wild one.
    assert err[0].max() < 0.02
    assert (err[1, 2] / 50.0).max() < 0.02


def test_quantize_pytree_covers_expert_banks():
    from distllm_tpu.ops.quantization import QTensor, quantize_pytree

    rng = np.random.default_rng(1)
    tree = {
        'layers': {
            'gate': {'kernel': jnp.asarray(rng.normal(size=(2, 4, 16, 8)), jnp.float32)},
            'router': {'kernel': jnp.asarray(rng.normal(size=(2, 16, 4)), jnp.float32)},
        }
    }
    out = quantize_pytree(tree, mode='int8', min_size=1)
    assert isinstance(out['layers']['gate']['kernel'], QTensor)
    # Routers are precision-sensitive and stay float.
    assert not isinstance(out['layers']['router']['kernel'], QTensor)


def test_abstract_quantizer_matches_real_for_expert_banks():
    import jax

    from distllm_tpu.ops.quantization import (
        quantize_pytree,
        quantize_pytree_abstract,
    )

    rng = np.random.default_rng(2)
    tree = {'gate': {'kernel': jnp.asarray(rng.normal(size=(2, 3, 16, 8)), jnp.float32)}}
    real = quantize_pytree(tree, mode='int8', min_size=1)
    shapes = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree
    )
    abstract = quantize_pytree_abstract(shapes, mode='int8', min_size=1)
    rq, aq = real['gate']['kernel'], abstract['gate']['kernel']
    assert tuple(rq.q.shape) == tuple(aq.q.shape)
    assert tuple(rq.scale.shape) == tuple(aq.scale.shape)
