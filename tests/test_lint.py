"""Tier-1 lint gate: a thin bridge onto the distlint framework.

The rules themselves live in ``distllm_tpu/analysis/`` (see
``docs/static_analysis.md``); this module's job is to keep tier-1
enforcing every one of them. The whole surface is parsed ONCE
(module-scoped project + one ``analyze`` pass feeding all rules — the
legacy version re-parsed the tree per rule, ~8×), then each rule gets
its own test function so a failure names the rule immediately.

ruff / mypy still run when installed (``pip install -e .[lint]``; this
image ships neither and has no egress), configured in pyproject.toml.
"""

from __future__ import annotations

import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from distllm_tpu.analysis import (
    META_RULE_IDS,
    RULES,
    analyze,
    iter_rules,
    load_project,
)
from distllm_tpu.analysis.core import SYNTAX_ERROR

REPO = Path(__file__).resolve().parent.parent

# All twelve registered rules, enforced in tier-1. Pinned by id so a rule
# silently falling out of the registry fails here instead of passing
# vacuously.
EXPECTED_RULES = frozenset(
    {
        'unused-import',
        'raw-print',
        'direct-free',
        'metric-name-catalog',
        'flight-kind-catalog',
        'trace-category-catalog',
        'compile-phase-catalog',
        'host-sync-in-hot-path',
        'traced-python-branch',
        'lock-discipline',
        'nondeterminism-in-dispatch',
        'swallowed-exception',
    }
)


@pytest.fixture(scope='module')
def findings() -> dict[str, list]:
    """One parse of the lint surface, one pass of every rule, shared by
    every test below — grouped by rule id (meta rules included)."""
    project = load_project(REPO)
    grouped: dict[str, list] = {
        rule_id: [] for rule_id in (*RULES, *META_RULE_IDS)
    }
    for diag in analyze(project, iter_rules()):
        grouped.setdefault(diag.rule_id, []).append(diag)
    return grouped


def _assert_clean(findings, rule_id: str) -> None:
    diags = findings[rule_id]
    assert not diags, (
        f'[{rule_id}] findings (see docs/static_analysis.md; suppress '
        'only with a justified "# distlint: disable=..." directive):\n'
        + '\n'.join(d.format() for d in diags)
    )


def test_registry_complete():
    assert EXPECTED_RULES == set(RULES), (
        'registered distlint rules drifted from the tier-1 contract'
    )


def test_everything_parses(findings):
    _assert_clean(findings, SYNTAX_ERROR)


@pytest.mark.parametrize('rule_id', sorted(EXPECTED_RULES))
def test_rule_clean(findings, rule_id):
    _assert_clean(findings, rule_id)


@pytest.mark.parametrize(
    'meta_id', [m for m in META_RULE_IDS if m != SYNTAX_ERROR]
)
def test_suppressions_audited(findings, meta_id):
    """Every suppression carries a justification, names a real rule, and
    actually matches a finding (the audit trail cannot rot)."""
    _assert_clean(findings, meta_id)


@pytest.mark.skipif(shutil.which('ruff') is None, reason='ruff not installed')
def test_ruff():
    proc = subprocess.run(
        ['ruff', 'check', 'distllm_tpu', 'tests', 'scripts'],
        cwd=REPO, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.skipif(shutil.which('mypy') is None, reason='mypy not installed')
def test_mypy():
    proc = subprocess.run(
        [sys.executable, '-m', 'mypy', 'distllm_tpu'],
        cwd=REPO, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
