"""Lint/type gate (reference rigor parity: tox runs ruff strict + mypy
strict, ``/root/reference`` tox.ini:1-15 — cited for provenance only).

Layered so something always enforces:

- ruff / mypy run when installed (``pip install -e .[lint]``; this image
  ships neither and has no egress), configured in pyproject.toml;
- an AST gate with zero dependencies runs everywhere: every source file
  must parse, and no module may carry unused imports (the most common
  rot this repo can accumulate; ruff F401 equivalent).
"""

from __future__ import annotations

import ast
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
SOURCES = sorted(
    list((REPO / 'distllm_tpu').rglob('*.py'))
    + list((REPO / 'scripts').glob('*.py'))
    + list((REPO / 'tests').glob('*.py'))
    + [REPO / 'bench.py', REPO / '__graft_entry__.py']
)


def test_everything_parses():
    for path in SOURCES:
        ast.parse(path.read_text(), filename=str(path))


def _imported_names(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split('.')[0]
                yield node.lineno, name
        elif isinstance(node, ast.ImportFrom):
            if node.module == '__future__':
                continue
            for alias in node.names:
                if alias.name == '*':
                    continue
                yield node.lineno, alias.asname or alias.name


def _used_names(tree: ast.AST) -> set[str]:
    used: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            inner = node
            while isinstance(inner, ast.Attribute):
                inner = inner.value
            if isinstance(inner, ast.Name):
                used.add(inner.id)
    # Names re-exported via __all__ strings count as used.
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == '__all__':
                    for el in getattr(node.value, 'elts', []):
                        if isinstance(el, ast.Constant):
                            used.add(str(el.value))
    return used


def test_no_unused_imports():
    offenders = []
    for path in SOURCES:
        if path.name == '__init__.py':
            continue  # package surface re-exports by design
        text = path.read_text()
        lines = text.splitlines()
        tree = ast.parse(text, filename=str(path))
        used = _used_names(tree)
        for lineno, name in _imported_names(tree):
            if name in used:
                continue
            line = lines[lineno - 1]
            # Only an F401 (or blanket) noqa exempts an unused import; a
            # noqa for an unrelated rule (e.g. E402) must not mask rot.
            if 'noqa: F401' in line or line.rstrip().endswith('# noqa'):
                continue  # deliberate side-effect import
            offenders.append(f'{path.relative_to(REPO)}:{lineno} {name}')
    assert not offenders, 'unused imports:\n' + '\n'.join(offenders)


def test_no_raw_print_telemetry():
    """Telemetry goes through ``observability.log_event`` (counted, greppable),
    not bare ``print(`` — which bypasses the metrics registry and is invisible
    to scrapes. Only ``timer.py`` (the legacy ``[timer]`` line emitter) and
    the ``observability`` package itself may print."""
    package = REPO / 'distllm_tpu'
    offenders = []
    for path in sorted(package.rglob('*.py')):
        relative = path.relative_to(package)
        if relative.name == 'timer.py' or relative.parts[0] == 'observability':
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == 'print'
            ):
                offenders.append(f'{path.relative_to(REPO)}:{node.lineno}')
    assert not offenders, (
        'raw print( telemetry (use distllm_tpu.observability.log_event):\n'
        + '\n'.join(offenders)
    )


def test_no_direct_block_free_outside_allocator_modules():
    """KV blocks are freed ONLY by the allocator/scheduler/prefix-cache
    machinery (``generate/engine/kv_cache.py`` + the scheduler bindings).
    A stray ``allocator.free(...)`` anywhere else can double-free a block
    that the prefix cache still maps — corruption that surfaces as another
    request's KV, long after the bad call. The AST gate forbids any
    ``X.free(...)`` attribute call in ``distllm_tpu`` outside those two
    modules (same spirit as the raw-print rule: the dangerous spelling is
    banned, the sanctioned paths are allowlisted)."""
    package = REPO / 'distllm_tpu'
    allowed = {
        ('generate', 'engine', 'kv_cache.py'),
        ('generate', 'engine', 'scheduler.py'),
    }
    offenders = []
    for path in sorted(package.rglob('*.py')):
        if path.relative_to(package).parts in allowed:
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == 'free'
            ):
                offenders.append(f'{path.relative_to(REPO)}:{node.lineno}')
    assert not offenders, (
        'direct .free( calls outside the allocator/cache modules '
        '(route block lifecycle through the scheduler/PrefixCache):\n'
        + '\n'.join(offenders)
    )


def _catalog_registered_names() -> set[str]:
    """Metric names registered in the instruments.py catalog: the first
    string argument of every ``*.counter/gauge/histogram(...)`` call."""
    tree = ast.parse(
        (REPO / 'distllm_tpu' / 'observability' / 'instruments.py').read_text()
    )
    names: set[str] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ('counter', 'gauge', 'histogram')
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            names.add(node.args[0].value)
    return names


def test_metric_names_registered_in_catalog():
    """Every ``distllm_*`` metric name referenced anywhere in the package
    (string literals — code AND docstrings) must be registered in the
    ``instruments.py`` catalog. Prevents silent series drift: a typo'd or
    ad-hoc ``registry.counter('distllm_...')`` at a call site would create
    a series the catalog (and docs/observability.md, and the
    first-scrape-full-schema guarantee) knows nothing about.

    Histogram references may use the exposition suffixes ``_bucket`` /
    ``_sum`` / ``_count`` of a registered base name.
    """
    import re

    registered = _catalog_registered_names()
    assert registered, 'catalog parse came back empty — rule is broken'
    # Full-literal matches only; 'distllm_tpu*' is the package itself, and
    # globs like 'distllm_prefix_cache_*' never match the name regex.
    name_re = re.compile(r'^distllm_[a-z0-9_]+$')
    suffix_re = re.compile(r'_(bucket|sum|count)$')
    offenders = []
    for path in sorted((REPO / 'distllm_tpu').rglob('*.py')):
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
            ):
                continue
            for word in re.findall(r'[A-Za-z0-9_]+', node.value):
                if (
                    not name_re.match(word)
                    or word.startswith('distllm_tpu')
                    or word.endswith('_')  # doc glob like distllm_foo_*
                ):
                    continue
                base = suffix_re.sub('', word)
                if word not in registered and base not in registered:
                    offenders.append(
                        f'{path.relative_to(REPO)}:{node.lineno} {word}'
                    )
    assert not offenders, (
        'distllm_* metric names not registered in the instruments.py '
        'catalog (add them there — the catalog is the series contract):\n'
        + '\n'.join(sorted(set(offenders)))
    )


def _frozenset_catalog(name: str) -> set[str]:
    """String members of a ``NAME = frozenset({...})`` catalog in
    ``instruments.py`` (AST-extracted, mirroring the metric-name catalog
    parser)."""
    tree = ast.parse(
        (REPO / 'distllm_tpu' / 'observability' / 'instruments.py').read_text()
    )
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if not (isinstance(tgt, ast.Name) and tgt.id == name):
                continue
            call = node.value  # frozenset({...})
            if isinstance(call, ast.Call) and call.args:
                return {
                    el.value
                    for el in getattr(call.args[0], 'elts', [])
                    if isinstance(el, ast.Constant)
                    and isinstance(el.value, str)
                }
    return set()


def _flight_kind_catalog() -> set[str]:
    return _frozenset_catalog('FLIGHT_KINDS')


def test_flight_record_kinds_registered_in_catalog():
    """Every FlightRecorder ``kind`` emitted in the package (a string
    literal — or a conditional between string literals — as the first
    argument of a ``.record(...)`` / ``_record_step(...)`` call) must be
    registered in the ``instruments.FLIGHT_KINDS`` catalog, mirroring the
    ``distllm_*`` metric-name rule. A kind minted at a call site would
    silently fragment the flight schema that debug bundles,
    ``/debug/flight``, and ``aggregate.py`` replay."""
    registered = _flight_kind_catalog()
    assert registered, 'FLIGHT_KINDS parse came back empty — rule is broken'
    offenders = []
    for path in sorted((REPO / 'distllm_tpu').rglob('*.py')):
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            func = node.func
            name = (
                func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name)
                else None
            )
            if name not in ('record', '_record_step'):
                continue
            first = node.args[0]
            branches = (
                (first.body, first.orelse)
                if isinstance(first, ast.IfExp)
                else (first,)
            )
            for branch in branches:
                if not (
                    isinstance(branch, ast.Constant)
                    and isinstance(branch.value, str)
                ):
                    continue
                if branch.value not in registered:
                    offenders.append(
                        f'{path.relative_to(REPO)}:{node.lineno} '
                        f'{branch.value}'
                    )
    assert not offenders, (
        'flight-record kinds not registered in instruments.FLIGHT_KINDS '
        '(add them there — the catalog is the flight-schema contract):\n'
        + '\n'.join(sorted(set(offenders)))
    )


def test_trace_event_categories_registered_in_catalog():
    """Every trace-event category the package emits (a string literal
    passed as a ``cat=...`` keyword or a ``'cat': ...`` dict key) must be
    registered in ``instruments.TRACE_EVENT_CATEGORIES``, mirroring the
    metric-name and flight-kind rules: a category minted at a call site
    would fragment the trace schema Perfetto queries, the exporter
    validator, and downstream tooling filter on."""
    registered = _frozenset_catalog('TRACE_EVENT_CATEGORIES')
    assert registered, (
        'TRACE_EVENT_CATEGORIES parse came back empty — rule is broken'
    )
    offenders = []
    for path in sorted((REPO / 'distllm_tpu').rglob('*.py')):
        tree = ast.parse(path.read_text(), filename=str(path))
        emitted: list[tuple[int, str]] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                for kw in node.keywords:
                    if (
                        kw.arg == 'cat'
                        and isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, str)
                    ):
                        emitted.append((node.lineno, kw.value.value))
            elif isinstance(node, ast.Dict):
                for key, value in zip(node.keys, node.values):
                    if (
                        isinstance(key, ast.Constant)
                        and key.value == 'cat'
                        and isinstance(value, ast.Constant)
                        and isinstance(value.value, str)
                    ):
                        emitted.append((node.lineno, value.value))
        for lineno, cat in emitted:
            if cat not in registered:
                offenders.append(
                    f'{path.relative_to(REPO)}:{lineno} {cat}'
                )
    assert not offenders, (
        'trace-event categories not registered in '
        'instruments.TRACE_EVENT_CATEGORIES (add them there — the '
        'catalog is the trace-schema contract):\n'
        + '\n'.join(sorted(set(offenders)))
    )


def test_compile_phase_kinds_registered_in_catalog():
    """Every startup/compile phase the package opens (a string literal as
    the first argument of a ``.phase(...)`` call —
    ``CompileWatcher.phase``) must be registered in
    ``instruments.COMPILE_PHASES``, mirroring the metric-name /
    flight-kind / trace-category rules: a phase minted at a call site
    would fragment the startup schema that debug bundles and the
    Perfetto startup track replay."""
    registered = _frozenset_catalog('COMPILE_PHASES')
    assert registered, (
        'COMPILE_PHASES parse came back empty — rule is broken'
    )
    offenders = []
    for path in sorted((REPO / 'distllm_tpu').rglob('*.py')):
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            func = node.func
            if not (
                isinstance(func, ast.Attribute) and func.attr == 'phase'
            ):
                continue
            first = node.args[0]
            if (
                isinstance(first, ast.Constant)
                and isinstance(first.value, str)
                and first.value not in registered
            ):
                offenders.append(
                    f'{path.relative_to(REPO)}:{node.lineno} {first.value}'
                )
    assert not offenders, (
        'compile-phase kinds not registered in instruments.COMPILE_PHASES '
        '(add them there — the catalog is the startup-schema contract):\n'
        + '\n'.join(sorted(set(offenders)))
    )


@pytest.mark.skipif(shutil.which('ruff') is None, reason='ruff not installed')
def test_ruff():
    proc = subprocess.run(
        ['ruff', 'check', 'distllm_tpu', 'tests', 'scripts'],
        cwd=REPO, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.skipif(shutil.which('mypy') is None, reason='mypy not installed')
def test_mypy():
    proc = subprocess.run(
        [sys.executable, '-m', 'mypy', 'distllm_tpu'],
        cwd=REPO, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
