"""RAG layer tests: response synthesizer, QA tasks, eval suite."""

import json

import pytest

from distllm_tpu.generate import get_generator
from distllm_tpu.rag.response_synthesizer import RagGenerator
from distllm_tpu.rag.tasks import TASKS, get_task
from distllm_tpu.rag.tasks.litqa import LitQATask, QuestionAnswerEntry
from distllm_tpu.rag.tasks.pubmedqa import PubmedQAEntry
from distllm_tpu.rag.tasks.sciq import SciQEntry


def _make_retriever(tmp_path):
    from datasets import Dataset

    from distllm_tpu.embed import get_encoder, get_pooler
    from distllm_tpu.embed.embedders.full_sequence import compute_embeddings
    from distllm_tpu.rag.search import RetrieverConfig

    encoder = get_encoder({'name': 'fake', 'embedding_size': 32})
    pooler = get_pooler({'name': 'mean'})
    texts = ['context about proteins', 'context about stars', 'context about cells']
    embeddings = compute_embeddings(texts, encoder, pooler, 2)
    Dataset.from_dict(
        {'text': texts, 'embeddings': [e for e in embeddings]}
    ).save_to_disk(str(tmp_path / 'corpus'))
    return RetrieverConfig(
        faiss_config={'dataset_dir': str(tmp_path / 'corpus')},
        encoder_config={'name': 'fake', 'embedding_size': 32},
        pooler_config={'name': 'mean'},
        batch_size=2,
    ).get_retriever()


def test_rag_generator_no_retriever():
    generator = RagGenerator(get_generator({'name': 'fake'}))
    out = generator.generate('what is a protein')
    assert out == ['response to: what is a protein']


def test_rag_generator_with_retrieval(tmp_path):
    retriever = _make_retriever(tmp_path)
    echo = get_generator(
        {'name': 'fake', 'response_template': '{prompt}', 'max_prompt_chars': 4000}
    )
    generator = RagGenerator(echo, retriever=retriever)
    from distllm_tpu.generate import get_prompt_template

    out = generator.generate(
        'context about proteins',
        prompt_template=get_prompt_template({'name': 'question_answer'}),
        retrieval_top_k=2,
        retrieval_score_threshold=-10.0,
    )
    # The echoed prompt should contain retrieved context lines with scores.
    assert 'context (with relevance scores)' in out[0]
    assert 'score:' in out[0]


# ------------------------------------------------------------------ tasks
def test_task_registry():
    assert set(TASKS) == {
        'litqa',
        'pubmedqa',
        'sciq',
        'protein_function_qa',
        'protein_interaction_qa',
    }
    with pytest.raises(ValueError):
        get_task('bogus', '/tmp')


def test_litqa_entry_multiple_choice():
    entry = QuestionAnswerEntry(
        question='What binds DNA',
        ideal='Histones',
        distractors=['Lipids', 'Sugars', 'Ions', 'Metals'],
    )
    assert entry.ideal == 'histones'  # lowercased by validator
    mc = entry.get_multiple_choice()
    assert mc.startswith('What binds DNA?\nOptions:\n1. ')
    assert 'histones' in mc
    assert mc.count('\n') >= 5


def test_litqa_entry_pads_missing_distractors():
    entry = QuestionAnswerEntry(question='Q?', ideal='A', distractors=['b'])
    mc = entry.get_multiple_choice()
    assert '4. ' in mc  # still four options


def test_pubmedqa_entry():
    entry = PubmedQAEntry(
        QUESTION='Does X work',
        CONTEXTS=['ctx1', 'ctx2'],
        final_decision='yes',
        LONG_ANSWER='ignored extra field',
    )
    mc = entry.get_multiple_choice()
    assert 'Most relevant context:' in mc
    assert 'ctx1\nctx2' in mc
    assert '1. yes\n2. no\n3. maybe' in mc


def test_sciq_entry_has_four_options():
    entry = SciQEntry(
        question='Which gas',
        distractor1='helium',
        distractor2='argon',
        distractor3='neon',
        correct_answer='oxygen',
    )
    mc = entry.get_multiple_choice()
    for option in ('oxygen', 'helium', 'argon', 'neon'):
        assert option in mc


def test_task_accuracy_precision(tmp_path):
    task = LitQATask.__new__(LitQATask)  # skip download plumbing
    assert task.compute_accuracy(['a', 'b'], ['a', 'c']) == 0.5
    precision = task.compute_precision(
        ['a', 'b', 'c'], ['a', 'i cannot answer.', 'c']
    )
    assert precision == 1.0  # abstention dropped; note: pairs stay aligned


def test_task_end_to_end_with_local_data(tmp_path, monkeypatch):
    """Full task.evaluate with a fake generator and a local litqa file."""
    data = [
        {
            'question': 'What is water',
            'ideal': 'H2O',
            'distractors': ['CO2', 'NaCl', 'O2'],
        }
    ]
    litqa_dir = tmp_path / 'litqa'
    litqa_dir.mkdir(parents=True)
    (litqa_dir / 'litqa.jsonl').write_text(
        '\n'.join(json.dumps(d) for d in data)
    )
    task = get_task('litqa', tmp_path)  # file exists -> download skipped
    generator = RagGenerator(
        get_generator({'name': 'fake', 'response_template': 'h2o'})
    )
    results = task.evaluate(generator)
    assert results == {'accuracy': 1.0, 'precision': 1.0}


def test_eval_suite(tmp_path):
    from distllm_tpu.rag.evaluate import EvalSuiteConfig, run_eval_suite
    from distllm_tpu.registry import registry

    litqa_dir = tmp_path / 'dl' / 'litqa'
    litqa_dir.mkdir(parents=True)
    (litqa_dir / 'litqa.jsonl').write_text(
        json.dumps(
            {'question': 'Q', 'ideal': 'x', 'distractors': ['y', 'z', 'w']}
        )
    )
    config = EvalSuiteConfig(
        rag_configs=[
            {
                'generator_config': {'name': 'fake', 'response_template': 'x'}
            }
        ],
        tasks=['litqa'],
        download_dir=tmp_path / 'dl',
        output_path=tmp_path / 'results.json',
    )
    results = run_eval_suite(config)
    assert results['model_0']['litqa']['accuracy'] == 1.0
    assert (tmp_path / 'results.json').exists()
    registry().clear()
