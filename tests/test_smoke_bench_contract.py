"""Bench driver-contract smoke tests (ISSUE 3 acceptance criteria).

Rounds 3–5 each lost the official record to a timeout because ``bench.py``
printed its single JSON line only after the last stage. These tests pin the
crash-proof contract on CPU with tiny budgets:

- every completed stage is durably checkpointed to ``BENCH_partial.jsonl``
  the moment it finishes;
- killing the orchestrator (SIGTERM — what the driver's ``timeout`` sends)
  while a later stage is mid-flight still emits ONE parseable
  driver-contract line carrying the completed stages' metrics;
- a stage that exceeds its budget is killed without losing earlier stages,
  and the final line is still emitted on normal exit.

The orchestrator subprocess is the real ``python bench.py`` — no test
doubles; ``DISTLLM_BENCH_TEST_HANG_STAGE`` parks the named stage before
its heavy imports so the kill paths run in seconds.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
BENCH = REPO / 'bench.py'


def _bench_env(tmp_path: Path, **extra: str) -> dict[str, str]:
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS='cpu',
        DISTLLM_BENCH_SMALL='1',
        DISTLLM_BENCH_RECORD_DIR=str(tmp_path),
        DISTLLM_BENCH_BUNDLE_DIR=str(tmp_path / 'bundles'),
        DISTLLM_BENCH_PROBE_ATTEMPTS='1',
        DISTLLM_BENCH_WATCHDOG_S='0',
    )
    env.update(extra)
    return env


def _wait_for_stage(partial: Path, stage: str, timeout_s: float) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if partial.exists() and f'"stage": "{stage}"' in partial.read_text():
            return
        time.sleep(0.5)
    pytest.fail(f'stage {stage!r} never reached {partial}')


def _last_json_line(stdout: str) -> dict:
    lines = [line for line in stdout.strip().splitlines() if line.strip()]
    assert lines, f'no stdout from bench: {stdout!r}'
    return json.loads(lines[-1])


def test_bench_sigterm_mid_stage_still_emits_contract_line(tmp_path):
    """Acceptance criterion: SIGTERM after >= 1 completed stage emits a
    parseable driver-contract line with that stage's metrics, and
    BENCH_partial.jsonl holds every completed stage."""
    partial = tmp_path / 'BENCH_partial.jsonl'
    proc = subprocess.Popen(
        [sys.executable, str(BENCH)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=_bench_env(
            tmp_path,
            DISTLLM_BENCH_STAGES='embed,gen',
            DISTLLM_BENCH_TEST_HANG_STAGE='gen',
            DISTLLM_BENCH_DEADLINE_S='600',
        ),
        cwd=REPO,
    )
    try:
        # embed completes and lands on disk while gen hangs mid-flight.
        _wait_for_stage(partial, 'embed', timeout_s=300)
        time.sleep(1)  # let the orchestrator enter the hung gen stage
        proc.send_signal(signal.SIGTERM)
        out, _err = proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()

    result = _last_json_line(out)
    # The completed embed stage's metrics survived the kill...
    assert result['metric'] == 'embeddings/sec/chip'
    assert result['value'] > 0
    assert result['unit'] == 'emb/s'
    assert 'embed' in result['stages_completed']
    assert 'gen' not in result['stages_completed']
    assert result['interrupted'] == 'sigterm'
    # ...and the on-disk record holds every completed stage.
    stages = [
        json.loads(line)['stage']
        for line in partial.read_text().splitlines()
        if line.strip()
    ]
    assert 'embed' in stages
    # The composed snapshot tracked the record.
    snapshot = json.loads((tmp_path / 'BENCH_snapshot.json').read_text())
    assert snapshot['value'] == result['value']


def test_bench_stage_timeout_truncates_but_never_zeroes(tmp_path):
    """A stage blowing its budget is killed; earlier stages' metrics and
    the final contract line survive, with the timeout recorded — and the
    probe satellite: every backend-probe attempt's outcome lands in the
    record (and therefore in the final line)."""
    proc = subprocess.run(
        [sys.executable, str(BENCH)],
        capture_output=True, text=True, timeout=420,
        env=_bench_env(
            tmp_path,
            DISTLLM_BENCH_STAGES='embed,gen',
            DISTLLM_BENCH_TEST_HANG_STAGE='gen',
            DISTLLM_BENCH_DEADLINE_S='600',
            # Per-stage budgets: embed runs for real; the hung gen (parked
            # before its imports by the hang hook) is killed in seconds.
            DISTLLM_BENCH_STAGE_TIMEOUT_S='{"embed": 300, "gen": 3}',
            DISTLLM_BENCH_STAGE_FLOOR_S='1',
        ),
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    result = _last_json_line(proc.stdout)
    assert result['value'] > 0
    assert result['stages_completed'] == ['embed']
    assert 'timed out' in result['gen_error']
    assert 'interrupted' not in result  # normal exit, not a signal
    # Probe-ladder satellite: attempts recorded with outcomes.
    attempt = result['probe_attempts'][0]
    assert attempt['outcome'] == 'ok'
    assert attempt['platform'] == 'cpu'
    assert 'elapsed_s' in attempt
