"""GPipe-style pipeline parallelism vs the serial layer scan.

The reference never exercises pipeline parallelism (config pass-through
only, SURVEY.md §2.5); these tests pin our stage-sharded microbatch
schedule to exact serial-scan numerics, forward and backward, on the
virtual CPU mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distllm_tpu.parallel.pipeline import (
    make_pipeline_mesh,
    pipeline_apply,
)


def _layer_fn(lp, x):
    # simple residual MLP layer: x + tanh(x @ w + b)
    return x + jnp.tanh(x @ lp['w'] + lp['b'])


def _stack(rng, n_layers, width):
    return {
        'w': jnp.asarray(
            rng.standard_normal((n_layers, width, width)) * 0.3, jnp.float32
        ),
        'b': jnp.asarray(rng.standard_normal((n_layers, width)) * 0.1, jnp.float32),
    }


def _serial(params, x):
    def body(x, lp):
        return _layer_fn(lp, x), None

    out, _ = jax.lax.scan(body, x, params)
    return out


@pytest.fixture(scope='module')
def pipe_mesh():
    return make_pipeline_mesh(4)


class TestPipeline:
    def test_matches_serial_scan(self, rng, pipe_mesh):
        params = _stack(rng, 8, 16)  # 2 layers per stage
        x = jnp.asarray(rng.standard_normal((12, 16)), jnp.float32)
        out = pipeline_apply(
            params, x, _layer_fn, pipe_mesh, num_microbatches=4
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(_serial(params, x)), atol=1e-5
        )

    def test_microbatch_count_one(self, rng, pipe_mesh):
        params = _stack(rng, 4, 8)
        x = jnp.asarray(rng.standard_normal((6, 8)), jnp.float32)
        out = pipeline_apply(
            params, x, _layer_fn, pipe_mesh, num_microbatches=1
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(_serial(params, x)), atol=1e-5
        )

    def test_gradients_match_serial(self, rng, pipe_mesh):
        params = _stack(rng, 4, 8)
        x = jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)

        def loss_pipe(p):
            return jnp.sum(
                pipeline_apply(p, x, _layer_fn, pipe_mesh, num_microbatches=2)
                ** 2
            )

        def loss_serial(p):
            return jnp.sum(_serial(p, x) ** 2)

        g_pipe = jax.grad(loss_pipe)(params)
        g_serial = jax.grad(loss_serial)(params)
        for a, b in zip(
            jax.tree_util.tree_leaves(g_pipe),
            jax.tree_util.tree_leaves(g_serial),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4
            )

    def test_jit_compatible(self, rng, pipe_mesh):
        params = _stack(rng, 4, 8)
        x = jnp.asarray(rng.standard_normal((4, 8)), jnp.float32)
        fn = jax.jit(
            lambda p, x: pipeline_apply(
                p, x, _layer_fn, pipe_mesh, num_microbatches=2
            )
        )
        np.testing.assert_allclose(
            np.asarray(fn(params, x)),
            np.asarray(_serial(params, x)),
            atol=1e-5,
        )

    def test_layer_divisibility_guard(self, rng, pipe_mesh):
        params = _stack(rng, 6, 8)  # 6 layers, 4 stages
        x = jnp.asarray(rng.standard_normal((4, 8)), jnp.float32)
        with pytest.raises(ValueError, match='not divisible'):
            pipeline_apply(params, x, _layer_fn, pipe_mesh)

    def test_batch_divisibility_guard(self, rng, pipe_mesh):
        params = _stack(rng, 4, 8)
        x = jnp.asarray(rng.standard_normal((5, 8)), jnp.float32)
        with pytest.raises(ValueError, match='microbatches'):
            pipeline_apply(params, x, _layer_fn, pipe_mesh, num_microbatches=4)

    def test_eight_stage_mesh(self, rng):
        mesh = make_pipeline_mesh(8)
        params = _stack(rng, 8, 8)  # 1 layer per stage
        x = jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)
        out = pipeline_apply(params, x, _layer_fn, mesh, num_microbatches=4)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(_serial(params, x)), atol=1e-5
        )
