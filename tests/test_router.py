"""Multi-replica router tests (docs/routing.md).

Covers the routing contract end-to-end over real sockets: rotation and
replica attribution, digest-affinity learning from response headers,
least-loaded fallback, ONE-WAY drain, retry-once on a dead replica, 429
passthrough — plus the replica-side surface (digest headers + /loadinfo
on chat_server) and the replica-aware Perfetto merge naming.
"""

from __future__ import annotations

import json
import socket
import threading
import time

import pytest

aiohttp = pytest.importorskip('aiohttp')
import requests  # noqa: E402
from aiohttp import web  # noqa: E402

from distllm_tpu.router import (  # noqa: E402
    AffinityMap,
    RouterConfig,
    build_router_app,
    prompt_prefix_digests,
)
from distllm_tpu.router.affinity import (  # noqa: E402
    HEADER_DEPTH,
    HEADER_DIGEST,
    HEADER_REPLICA,
    HEADER_RETRY,
    prompt_prefix_bytes,
)

# ----------------------------------------------------------- test servers


def _serve(app):
    """Boot an aiohttp app on a free port in a daemon thread; returns
    ``(base_url, stop)``. Same shape as tests/test_chat.py's helper but
    app-generic — the router tests boot stubs AND routers with it."""
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        port = s.getsockname()[1]
    holder: dict = {}

    def run():
        import asyncio

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        holder['loop'] = loop
        runner = web.AppRunner(app, shutdown_timeout=1.0)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, '127.0.0.1', port)
        loop.run_until_complete(site.start())
        holder['runner'] = runner
        loop.run_forever()

    thread = threading.Thread(target=run, daemon=True)
    thread.start()
    for _ in range(100):
        try:
            requests.get(f'http://127.0.0.1:{port}/health', timeout=1)
            break
        except Exception:
            time.sleep(0.05)
    done = {'stopped': False}

    def stop():
        if done['stopped']:
            return
        done['stopped'] = True
        loop = holder['loop']

        async def _shutdown():
            await holder['runner'].cleanup()
            loop.stop()

        loop.call_soon_threadsafe(lambda: loop.create_task(_shutdown()))
        thread.join(timeout=10)

    return f'http://127.0.0.1:{port}', stop


def _stub_replica(*, reply_status=200, retry_after='7'):
    """Minimal chat_server-shaped stub: /health, /loadinfo, and a
    completions handler that annotates digest headers exactly like the
    real replica. Returns ``(app, state, calls)`` — flip ``state`` keys
    to drive health transitions; ``calls`` records request bodies."""
    state = {
        'ready': True,
        'draining': False,
        'loadinfo': {'queue_depth': 0, 'in_flight': 0, 'kv_occupancy': 0.0},
    }
    calls: list[dict] = []

    async def health(request):
        return web.json_response(
            {'ready': state['ready'], 'draining': state['draining']}
        )

    async def loadinfo(request):
        return web.json_response(state['loadinfo'])

    async def completions(request):
        body = await request.json()
        calls.append(body)
        if reply_status == 429:
            return web.json_response(
                {'error': {'message': 'queue full', 'type': 'overloaded'}},
                status=429,
                headers={'Retry-After': retry_after},
            )
        headers = {}
        chain = prompt_prefix_digests(body.get('messages', []))
        if chain:
            headers[HEADER_DIGEST] = chain[-1].hex()
            headers[HEADER_DEPTH] = str(len(chain))
        return web.json_response(
            {'choices': [{'message': {'content': 'ok',
                                      'role': 'assistant'}}]},
            headers=headers,
        )

    app = web.Application()
    app.router.add_get('/health', health)
    app.router.add_get('/loadinfo', loadinfo)
    app.router.add_post('/v1/chat/completions', completions)
    return app, state, calls


def _router(urls, policy, **overrides):
    config = RouterConfig(
        replicas=tuple(urls),
        policy=policy,
        loadinfo_ttl_s=overrides.pop('loadinfo_ttl_s', 0.01),
        health_interval_s=overrides.pop('health_interval_s', 30.0),
        request_timeout_s=10.0,
        **overrides,
    )
    return _serve(build_router_app(config))


def _messages(text: str) -> list[dict]:
    return [{'role': 'user', 'content': text}]


def _post(url, messages, **body):
    return requests.post(
        f'{url}/v1/chat/completions',
        json={'messages': messages, **body},
        timeout=10,
    )


# ------------------------------------------------------- digest affinity


def test_prompt_prefix_digests_shared_prefix_shared_chain():
    # Rendered bytes: 'user\x1f' + content + '\x1e'. A 150-char shared
    # prefix fills 2 full 64-byte blocks; the 100-char distinct tails
    # land inside later FULL blocks (the chain emits full blocks only,
    # so a divergence past the last full block would be invisible).
    shared = 'x' * 150
    chain_a = prompt_prefix_digests(_messages(shared + 'a' * 100))
    chain_b = prompt_prefix_digests(_messages(shared + 'b' * 100))
    assert chain_a and chain_b
    shared_blocks = (5 + len(shared)) // 64
    assert shared_blocks == 2
    assert chain_a[:shared_blocks] == chain_b[:shared_blocks]
    assert chain_a != chain_b
    # Byte rendering is injective on (role, content) boundaries.
    assert prompt_prefix_bytes(_messages('ab')) != prompt_prefix_bytes(
        [{'role': 'usera', 'content': 'b'}]
    )


def test_affinity_map_verify_and_learn():
    chain = prompt_prefix_digests(_messages('y' * 300))
    assert len(chain) >= 2
    amap = AffinityMap()
    # Untrusted header must MATCH the locally computed chain to be
    # learned: wrong hex, malformed hex, and out-of-range depths all
    # teach nothing.
    assert amap.verify_and_learn('r1', chain, 'ff' * 32, str(len(chain))) == 0
    assert amap.verify_and_learn('r1', chain, 'zz', '1') == 0
    assert amap.verify_and_learn('r1', chain, chain[-1].hex(), '0') == 0
    assert (
        amap.verify_and_learn('r1', chain, chain[-1].hex(),
                              str(len(chain) + 1))
        == 0
    )
    assert amap.score('r1', chain) == 0
    depth = amap.verify_and_learn(
        'r1', chain, chain[-1].hex(), str(len(chain))
    )
    assert depth == len(chain)
    assert amap.score('r1', chain) == len(chain)
    assert amap.score('r2', chain) == 0
    amap.drop('r1')
    assert amap.score('r1', chain) == 0


def test_affinity_map_lru_bound():
    amap = AffinityMap(max_entries_per_replica=4)
    chains = [
        prompt_prefix_digests(_messages(f'session-{i} ' + 'z' * 100))
        for i in range(6)
    ]
    for chain in chains:
        amap.learn('r1', chain)
    assert amap.entries() <= 4
    # The oldest chains fell off; the newest survive.
    assert amap.score('r1', chains[-1]) >= 1
    assert amap.score('r1', chains[0]) == 0


# --------------------------------------------------------- routing policy


def test_round_robin_rotation_and_replica_header():
    app_a, _, calls_a = _stub_replica()
    app_b, _, calls_b = _stub_replica()
    url_a, stop_a = _serve(app_a)
    url_b, stop_b = _serve(app_b)
    router_url, stop_r = _router([url_a, url_b], 'round_robin')
    try:
        replicas_seen = []
        for i in range(4):
            resp = _post(router_url, _messages(f'req {i}'))
            assert resp.status_code == 200
            replicas_seen.append(resp.headers[HEADER_REPLICA])
        assert len(calls_a) == 2 and len(calls_b) == 2
        assert len(set(replicas_seen)) == 2
    finally:
        stop_r(), stop_a(), stop_b()


def test_prefix_affinity_pins_sessions_after_learning():
    app_a, _, calls_a = _stub_replica()
    app_b, _, calls_b = _stub_replica()
    url_a, stop_a = _serve(app_a)
    url_b, stop_b = _serve(app_b)
    router_url, stop_r = _router([url_a, url_b], 'prefix_affinity')
    try:
        session_text = 'session-alpha ' + 'p' * 150
        first = _post(router_url, _messages(session_text + ' turn 0'))
        assert first.status_code == 200
        home = first.headers[HEADER_REPLICA]
        # The digest headers from the first response taught the router
        # this session's residency: every repeat goes home.
        for turn in range(1, 4):
            resp = _post(
                router_url, _messages(session_text + f' turn {turn}')
            )
            assert resp.status_code == 200
            assert resp.headers[HEADER_REPLICA] == home
    finally:
        stop_r(), stop_a(), stop_b()


def test_least_loaded_fallback_prefers_light_queue():
    app_a, state_a, calls_a = _stub_replica()
    app_b, _, calls_b = _stub_replica()
    state_a['loadinfo'] = {
        'queue_depth': 5, 'in_flight': 3, 'kv_occupancy': 0.9
    }
    url_a, stop_a = _serve(app_a)
    url_b, stop_b = _serve(app_b)
    router_url, stop_r = _router([url_a, url_b], 'least_loaded')
    try:
        for i in range(3):
            resp = _post(router_url, _messages(f'cold {i}'))
            assert resp.status_code == 200
        assert len(calls_b) == 3 and len(calls_a) == 0
    finally:
        stop_r(), stop_a(), stop_b()


def test_drain_is_one_way_and_gets_no_new_requests():
    app_a, state_a, calls_a = _stub_replica()
    app_b, _, calls_b = _stub_replica()
    url_a, stop_a = _serve(app_a)
    url_b, stop_b = _serve(app_b)
    router_url, stop_r = _router(
        [url_a, url_b], 'round_robin', health_interval_s=0.05
    )
    try:
        state_a['draining'] = True
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            doc = requests.get(f'{router_url}/health', timeout=5).json()
            if 'draining' in doc['replicas'].values():
                break
            time.sleep(0.05)
        else:
            pytest.fail('router never observed the drain')
        before = len(calls_a)
        for i in range(4):
            assert _post(router_url, _messages(f'r {i}')).status_code == 200
        assert len(calls_a) == before  # zero NEW requests while draining
        assert len(calls_b) >= 4
        # One-way: the replica answering ready again must NOT rejoin —
        # its process restart means its cache (and its drained state's
        # reason) are gone; rotation re-entry is an operator action.
        state_a['draining'] = False
        state_a['ready'] = True
        time.sleep(0.3)
        before = len(calls_a)
        for i in range(3):
            assert _post(router_url, _messages(f's {i}')).status_code == 200
        assert len(calls_a) == before
        doc = requests.get(f'{router_url}/health', timeout=5).json()
        assert 'draining' in doc['replicas'].values()
    finally:
        stop_r(), stop_a(), stop_b()


def test_dead_replica_retry_once_with_marker():
    app_a, _, _ = _stub_replica()
    app_b, _, calls_b = _stub_replica()
    url_a, stop_a = _serve(app_a)
    url_b, stop_b = _serve(app_b)
    # Probes effectively off: the router must DISCOVER the death on the
    # proxy path. round_robin's first pick is replicas[0] — the corpse.
    router_url, stop_r = _router([url_a, url_b], 'round_robin')
    try:
        stop_a()
        resp = _post(router_url, _messages('failover me'))
        assert resp.status_code == 200
        assert resp.headers[HEADER_RETRY] == '1'
        assert resp.headers[HEADER_REPLICA] == url_b.split('//', 1)[1]
        assert len(calls_b) == 1
        # The dead replica left rotation: no more retry markers.
        resp = _post(router_url, _messages('again'))
        assert resp.status_code == 200
        assert HEADER_RETRY not in resp.headers
    finally:
        stop_r(), stop_a(), stop_b()


def test_429_propagates_untouched_and_is_not_retried():
    app_a, _, calls_a = _stub_replica(reply_status=429, retry_after='9')
    app_b, _, calls_b = _stub_replica()
    url_a, stop_a = _serve(app_a)
    url_b, stop_b = _serve(app_b)
    router_url, stop_r = _router([url_a, url_b], 'round_robin')
    try:
        statuses = [
            _post(router_url, _messages(f'r {i}')) for i in range(2)
        ]
        rejected = [r for r in statuses if r.status_code == 429]
        assert len(rejected) == 1  # round robin: exactly one hit the
        # admission-controlled replica, and its refusal was NOT moved
        # elsewhere (retrying defeats admission control)
        assert rejected[0].headers['Retry-After'] == '9'
        assert rejected[0].json()['error']['type'] == 'overloaded'
        assert HEADER_RETRY not in rejected[0].headers
        assert len(calls_a) == 1 and len(calls_b) == 1
    finally:
        stop_r(), stop_a(), stop_b()


def test_router_health_reports_states():
    app_a, _, _ = _stub_replica()
    url_a, stop_a = _serve(app_a)
    router_url, stop_r = _router([url_a], 'prefix_affinity')
    try:
        doc = requests.get(f'{router_url}/health', timeout=5).json()
        assert doc['ready'] is True
        assert doc['policy'] == 'prefix_affinity'
        assert list(doc['replicas'].values()) == ['healthy']
    finally:
        stop_r(), stop_a()


# ------------------------------------------------------ replica surface


def test_chat_server_digest_headers_and_loadinfo():
    from distllm_tpu.chat import ChatAppConfig
    from distllm_tpu.chat_server import build_app
    from distllm_tpu.registry import registry

    url, stop = _serve(build_app(ChatAppConfig()))
    try:
        messages = _messages('q' * 200)
        resp = requests.post(
            f'{url}/v1/chat/completions',
            json={'messages': messages},
            timeout=10,
        )
        assert resp.status_code == 200
        chain = prompt_prefix_digests(messages)
        assert resp.headers[HEADER_DIGEST] == chain[-1].hex()
        assert int(resp.headers[HEADER_DEPTH]) == len(chain)

        info = requests.get(f'{url}/loadinfo', timeout=5).json()
        assert info['ready'] is True and info['draining'] is False
        # The fake generator has no engine: load fields degrade to the
        # idle shape rather than erroring.
        assert info['queue_depth'] == 0
        assert 0.0 <= info['kv_occupancy'] <= 1.0
        assert isinstance(info['in_flight'], int)
    finally:
        stop()
        registry().clear()


# -------------------------------------------------- replica-aware merge


def test_host_label_parses_replica_ids(tmp_path):
    from distllm_tpu.observability.aggregate import host_label

    # Generic stems take the parent (the replica/host id)…
    assert host_label('bundle/replica-0/flight.jsonl') == 'replica-0'
    assert host_label('bundle/replica-1/spans.jsonl') == 'replica-1'
    # …distinctive stems keep themselves.
    assert host_label('logs/capture-host3.jsonl') == 'capture-host3'
    # Collisions stay distinguishable: the stem is appended first, then
    # an index once THAT collides too.
    seen: set = set()
    assert host_label('a/replica-0/flight.jsonl', seen) == 'replica-0'
    assert (
        host_label('b/replica-0/flight.jsonl', seen) == 'replica-0/flight'
    )
    assert (
        host_label('c/replica-0/flight.jsonl', seen)
        == 'replica-0/flight#2'
    )


def test_combined_perfetto_merge_names_replicas(tmp_path):
    from distllm_tpu.observability.aggregate import write_combined_perfetto

    paths = []
    for r in range(2):
        d = tmp_path / f'replica-{r}'
        d.mkdir()
        path = d / 'flight.jsonl'
        records = [
            {'kind': 'prefill', 't_wall': 100.0 + r, 'duration_s': 0.05,
             'batch': 1, 'tokens': 32},
            {'kind': 'decode', 't_wall': 100.2 + r, 'duration_s': 0.02,
             'batch': 1, 'tokens': 4},
        ]
        path.write_text(
            '\n'.join(json.dumps(rec) for rec in records) + '\n'
        )
        paths.append(path)
    out = tmp_path / 'combined.json'
    assert write_combined_perfetto(paths, out) == 2
    doc = json.loads(out.read_text())
    process_names = {
        e['args']['name'] for e in doc['traceEvents']
        if e['ph'] == 'M' and e['name'] == 'process_name'
    }
    # The fix under test: N identical 'flight.jsonl' basenames would
    # have collapsed into one unreadable process group.
    assert process_names == {'replica-0', 'replica-1'}
