"""ESM-Cambrian: independent-NumPy golden forward, checkpoint conversion,
tokenizer framing, encoder wiring (reference: embed/encoders/esmc.py).

Real released weights cannot be fetched here (zero egress), so the golden
check re-implements the published architecture equations independently in
NumPy over a synthetic esm-package-format state dict — catching both
conversion-naming and wiring mistakes.
"""

from __future__ import annotations

import numpy as np
import pytest

from distllm_tpu.models import esmc


def _synthetic_state(cfg: esmc.EsmcConfig, rng) -> dict[str, np.ndarray]:
    """An esm-package-shaped ESMC state dict with random weights."""
    h, f = cfg.hidden_size, cfg.ffn_hidden
    state = {'embed.weight': rng.normal(size=(cfg.vocab_size, h)).astype(np.float32) * 0.1}
    for i in range(cfg.num_layers):
        p = f'transformer.blocks.{i}'
        state[f'{p}.attn.layernorm_qkv.0.weight'] = rng.normal(size=(h,)).astype(np.float32) * 0.1 + 1
        state[f'{p}.attn.layernorm_qkv.0.bias'] = rng.normal(size=(h,)).astype(np.float32) * 0.1
        state[f'{p}.attn.layernorm_qkv.1.weight'] = rng.normal(size=(3 * h, h)).astype(np.float32) * 0.05
        state[f'{p}.attn.out_proj.weight'] = rng.normal(size=(h, h)).astype(np.float32) * 0.05
        state[f'{p}.attn.q_ln.weight'] = rng.normal(size=(h,)).astype(np.float32) * 0.1 + 1
        state[f'{p}.attn.k_ln.weight'] = rng.normal(size=(h,)).astype(np.float32) * 0.1 + 1
        state[f'{p}.ffn.0.weight'] = rng.normal(size=(h,)).astype(np.float32) * 0.1 + 1
        state[f'{p}.ffn.0.bias'] = rng.normal(size=(h,)).astype(np.float32) * 0.1
        state[f'{p}.ffn.1.weight'] = rng.normal(size=(2 * f, h)).astype(np.float32) * 0.05
        state[f'{p}.ffn.3.weight'] = rng.normal(size=(h, f)).astype(np.float32) * 0.05
    state['transformer.norm.weight'] = rng.normal(size=(h,)).astype(np.float32) * 0.1 + 1
    return state


def _numpy_reference(state, cfg, ids, mask):
    """Independent NumPy ESM-C forward (published architecture equations)."""

    def ln(x, w, b=None, eps=1e-5):
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        out = (x - mu) / np.sqrt(var + eps) * w
        return out + b if b is not None else out

    def rope(x):  # [B, S, N, Hd], rotate-half, theta 1e4
        b, s, n, hd = x.shape
        inv = 1.0 / (10000.0 ** (np.arange(0, hd, 2) / hd))
        freqs = np.outer(np.arange(s), inv)  # [S, Hd/2]
        cos, sin = np.cos(freqs), np.sin(freqs)
        x1, x2 = x[..., : hd // 2], x[..., hd // 2 :]
        cos = cos[None, :, None, :]
        sin = sin[None, :, None, :]
        return np.concatenate(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
        )

    h = cfg.hidden_size
    n, hd = cfg.num_heads, cfg.head_size
    scale = np.sqrt(cfg.num_layers / 36.0)
    x = state['embed.weight'][ids]
    key_mask = mask[:, None, None, :].astype(bool)  # [B,1,1,S]
    for i in range(cfg.num_layers):
        p = f'transformer.blocks.{i}'
        normed = ln(
            x,
            state[f'{p}.attn.layernorm_qkv.0.weight'],
            state[f'{p}.attn.layernorm_qkv.0.bias'],
        )
        qkv = normed @ state[f'{p}.attn.layernorm_qkv.1.weight'].T
        q, k, v = np.split(qkv, 3, axis=-1)
        q = ln(q, state[f'{p}.attn.q_ln.weight'])
        k = ln(k, state[f'{p}.attn.k_ln.weight'])
        b, s, _ = q.shape
        q = rope(q.reshape(b, s, n, hd))
        k = rope(k.reshape(b, s, n, hd))
        v = v.reshape(b, s, n, hd)
        scores = np.einsum('bqnd,bknd->bnqk', q, k) / np.sqrt(hd)
        scores = np.where(key_mask, scores, -1e30)
        probs = np.exp(scores - scores.max(-1, keepdims=True))
        probs = probs / probs.sum(-1, keepdims=True)
        attn = np.einsum('bnqk,bknd->bqnd', probs, v).reshape(b, s, h)
        x = x + (attn @ state[f'{p}.attn.out_proj.weight'].T) / scale
        normed2 = ln(
            x, state[f'{p}.ffn.0.weight'], state[f'{p}.ffn.0.bias']
        )
        gate_up = normed2 @ state[f'{p}.ffn.1.weight'].T
        gate, up = np.split(gate_up, 2, axis=-1)
        silu = gate / (1 + np.exp(-gate))
        x = x + ((silu * up) @ state[f'{p}.ffn.3.weight'].T) / scale
    return ln(x, state['transformer.norm.weight'])


@pytest.fixture
def tiny_cfg():
    return esmc.EsmcConfig(
        vocab_size=33, hidden_size=48, num_layers=3, num_heads=4,
        max_position_embeddings=32, dtype='float32',
    )


def test_esmc_matches_independent_numpy_reference(tiny_cfg, rng):
    state = _synthetic_state(tiny_cfg, rng)
    params = esmc.params_from_esm(state, tiny_cfg)
    ids = np.array([[0, 5, 6, 7, 2, 1, 1], [0, 9, 10, 2, 1, 1, 1]], np.int32)
    mask = (ids != 1).astype(np.int32)
    ours = np.asarray(esmc.apply(params, tiny_cfg, ids, mask))
    ref = _numpy_reference(state, tiny_cfg, ids, mask)
    valid = mask.astype(bool)
    np.testing.assert_allclose(ours[valid], ref[valid], rtol=1e-4, atol=1e-4)


def test_esmc_config_sizes():
    c300 = esmc.EsmcConfig.from_hidden_size(960)
    assert (c300.num_layers, c300.num_heads, c300.ffn_hidden) == (30, 15, 2560)
    assert abs(c300.residue_scale - np.sqrt(30 / 36)) < 1e-9
    c600 = esmc.EsmcConfig.from_hidden_size(1152)
    assert (c600.num_layers, c600.num_heads, c600.ffn_hidden) == (36, 18, 3072)
    with pytest.raises(ValueError, match='hidden size'):
        esmc.EsmcConfig.from_hidden_size(768)


def test_esmc_tokenizer_framing():
    tok = esmc.EsmcSequenceTokenizer(model_max_length=16)
    batch = tok(['MKV', 'ACDEFGHIKLMNPQRSTVWY'])
    ids, mask = batch.input_ids, batch.attention_mask
    # cls + body + eos framing.
    assert ids[0][0] == tok.cls_id
    assert ids[0][int(mask[0].sum()) - 1] == tok.eos_id
    # 2048-style cap: the long row truncates to max_length with eos kept.
    assert int(mask[1].sum()) == 16
    assert ids[1][15] == tok.eos_id
    # Round trip of the short sequence.
    assert tok.decode(ids[0][: int(mask[0].sum())]) == 'MKV'
    # Unknown characters map to <unk>, not a crash.
    weird = tok(['M*V'])
    assert weird.input_ids[0][2] == tok.unk_id


def test_esmc_encoder_from_pth_checkpoint(tmp_path, rng):
    """Encoder loads an esm-package-format .pth and embeds sequences."""
    torch = pytest.importorskip('torch')

    from distllm_tpu.embed import get_encoder, get_pooler
    from distllm_tpu.embed.embedders.full_sequence import compute_embeddings

    cfg = esmc.EsmcConfig.from_hidden_size(960, dtype='float32')
    cfg.num_layers = 2  # tiny stack, real dims
    state = _synthetic_state(
        esmc.EsmcConfig(
            vocab_size=64, hidden_size=960, num_layers=2, num_heads=15,
        ),
        rng,
    )
    ckpt_dir = tmp_path / 'esmc-300m-2024-12' / 'data' / 'weights'
    ckpt_dir.mkdir(parents=True)
    torch.save(
        {k: torch.from_numpy(v) for k, v in state.items()},
        ckpt_dir / 'esmc_300m_2024_12_v0.pth',
    )

    encoder = get_encoder(
        {
            'name': 'esmc',
            'pretrained_model_name_or_path': str(tmp_path / 'esmc-300m-2024-12'),
            'half_precision': False,
        }
    )
    # Patch the tiny depth in (full 30-layer random init is wastefully slow
    # for CI); dims/validation ran against the real 960 register.
    assert encoder.embedding_size == 960
    pooler = get_pooler({'name': 'mean'})
    out = compute_embeddings(['MKVL', 'ACD'], encoder, pooler, batch_size=2)
    assert out.shape == (2, 960)
    assert np.isfinite(out).all()


def test_esmc_encoder_rejects_unknown_name():
    from distllm_tpu.embed.encoders.esm2 import EsmCambrianEncoderConfig

    with pytest.raises(ValueError, match='Valid model names'):
        EsmCambrianEncoderConfig(
            pretrained_model_name_or_path='/some/finetune'
        ).resolved_embedding_size()
    cfg = EsmCambrianEncoderConfig(
        pretrained_model_name_or_path='/some/finetune', embedding_size=960
    )
    assert cfg.resolved_embedding_size() == 960
