"""Semantic protein search over a prebuilt embedding index.

The analogue of the reference's ``examples/protein_search.py`` (FASTA
queries -> ESM encoder -> FAISS search): here queries embed through the
JAX ESM-2/ESM-C encoders and hit the exact MXU inner-product index
(``distllm_tpu.rag.search``). The index is built beforehand by the embed
pipeline, e.g.::

    python -m distllm_tpu.distributed_embedding \
        --config examples/embed/esm2.fasta.workstation.yaml

Then::

    python examples/protein_search.py \
        --dataset_dir /results/esm2_embeddings/merged \
        --encoder esm2 \
        --checkpoint /checkpoints/esm2_t33_650M_UR50D \
        --fasta queries.fasta --top_k 5
"""

from __future__ import annotations

import argparse
import json
from pathlib import Path

from distllm_tpu.utils import apply_platform_env


def main() -> None:
    apply_platform_env()
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset_dir', type=Path, required=True,
                        help='Merged embedding dataset (build via embed + merge).')
    parser.add_argument('--fasta', type=Path, required=True,
                        help='FASTA file of query sequences.')
    parser.add_argument('--encoder', choices=['esm2', 'esmc', 'fake'],
                        default='esm2',
                        help="'fake' runs checkpoint-free (CI smoke).")
    parser.add_argument('--checkpoint', default=None,
                        help='Local encoder checkpoint directory '
                        '(required unless --encoder fake).')
    parser.add_argument('--top_k', type=int, default=5)
    parser.add_argument('--batch_size', type=int, default=8)
    parser.add_argument('--precision', choices=['float32', 'ubinary'],
                        default='float32')
    parser.add_argument('--output', type=Path, default=None,
                        help='Write JSONL results here (default: stdout).')
    parser.add_argument('--fake_embedding_size', type=int, default=16,
                        help='Embedding size for --encoder fake.')
    args = parser.parse_args()
    if args.encoder != 'fake' and not args.checkpoint:
        parser.error('--checkpoint is required unless --encoder fake')

    from distllm_tpu.embed.datasets.fasta import read_fasta
    from distllm_tpu.rag.search import RetrieverConfig

    retriever = RetrieverConfig(
        faiss_config={
            'name': 'tpu_index_v2',
            'dataset_dir': str(args.dataset_dir),
            'precision': args.precision,
        },
        encoder_config=(
            {'name': 'fake', 'embedding_size': args.fake_embedding_size}
            if args.encoder == 'fake'
            else {
                'name': args.encoder,
                'pretrained_model_name_or_path': args.checkpoint,
            }
        ),
        pooler_config={'name': 'mean'},
        batch_size=args.batch_size,
    ).get_retriever()

    sequences = read_fasta(args.fasta)
    queries = [seq.sequence for seq in sequences]
    results, _ = retriever.search(queries, top_k=args.top_k)

    out = args.output.open('w') if args.output else None
    for seq, scores, indices in zip(
        sequences, results.total_scores, results.total_indices
    ):
        hits = [
            {
                'score': float(score),
                'tag': tag,
            }
            for score, tag in zip(
                scores, retriever.get(list(indices), 'tags')
            )
        ]
        line = json.dumps({'query_tag': seq.tag, 'hits': hits})
        print(line, file=out or None)
    if out:
        out.close()
        print(f'wrote {len(queries)} query results to {args.output}')


if __name__ == '__main__':
    main()
