#!/usr/bin/env bash
# Manual pod bring-up (no batch scheduler): start one fabric worker per TPU
# host, all dialing the driver's coordinator. With PBSPro/Slurm, prefer the
# `pbspro`/`slurm` compute configs, which render and submit this for you.
#
# Usage:
#   on the driver host : python -m distllm_tpu.distributed_embedding \
#                          --config my_config.yaml      # compute_config: pod
#   on each TPU host   : bash examples/pod/launch_pod.sh tcp://driver:5555
#
# Or fan out over N hosts from one shell (requires passwordless ssh):
#   bash examples/pod/launch_pod.sh tcp://driver:5555 host1 host2 host3 ...
set -euo pipefail

COORDINATOR=${1:?usage: launch_pod.sh tcp://driver:5555 [host ...]}
shift || true

WORKER_CMD="python -m distllm_tpu.parallel.worker --coordinator ${COORDINATOR}"

if [ $# -eq 0 ]; then
    exec ${WORKER_CMD}
fi

for host in "$@"; do
    echo "[launch_pod] starting worker on ${host}"
    ssh "${host}" "JAX_PLATFORMS=tpu nohup ${WORKER_CMD} \
        > /tmp/distllm_worker.log 2>&1 &" &
done
wait
echo "[launch_pod] ${#} workers launched against ${COORDINATOR}"
